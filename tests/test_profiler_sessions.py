"""Profiler sessions: host trace-ring isolation, the PADDLE_TPU_TRACE
global enable, chrome-export schema, the export_chrome_tracing handler
(it must actually write the trace), and scheduler-driven capture
windows (make_scheduler → CLOSED/READY/RECORD with skip_first/repeat).
"""
import importlib.util
import json
import os

import paddle_tpu as pt
from paddle_tpu import profiler
from paddle_tpu.utils import trace


class TestTraceRingSessions:
    def test_second_session_does_not_export_first_sessions_spans(
            self, tmp_path):
        """Session isolation: the ring is shared, but each Profiler
        session exports only events recorded after its own start
        (the _t_session filter)."""
        with profiler.Profiler(timer_only=True) as p1:
            with profiler.record_span("first-session-only"):
                pass
        path1 = str(tmp_path / "t1.json")
        p1.export(path1)
        assert "first-session-only" in open(path1).read()

        with profiler.Profiler(timer_only=True) as p2:
            with profiler.record_span("second-session-only"):
                pass
        path2 = str(tmp_path / "t2.json")
        p2.export(path2)
        raw2 = open(path2).read()
        assert "second-session-only" in raw2
        assert "first-session-only" not in raw2

    def test_global_env_enable(self, monkeypatch):
        """PADDLE_TPU_TRACE=1 enables the ring at import time — no
        Profiler session needed. Loaded as a fresh module instance so
        the env var is actually read."""
        monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
        src = os.path.join(os.path.dirname(trace.__file__), "trace.py")
        spec = importlib.util.spec_from_file_location("_trace_fresh", src)
        fresh = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fresh)
        assert fresh.enabled()
        fresh.record("global-span", 0.001)
        assert "global-span" in fresh.summary()
        monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
        fresh2 = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fresh2)
        assert not fresh2.enabled()

    def test_chrome_export_schema(self, tmp_path):
        """The export is valid Trace Event Format: every event carries
        name/ph/pid/tid/ts, complete events carry dur, and span
        identity rides in args."""
        from paddle_tpu.observability import trace_context as tc
        with profiler.Profiler(timer_only=True) as p:
            with tc.bind("schema-req"):
                with profiler.record_span("schema-span"):
                    _ = (pt.ones([8, 8]) @ pt.ones([8, 8])).numpy()
        path = str(tmp_path / "schema.json")
        p.export(path)
        doc = json.loads(open(path).read())
        assert doc["displayTimeUnit"] == "ms"
        evs = doc["traceEvents"]
        assert evs, "empty export"
        for e in evs:
            assert {"name", "ph", "pid", "tid"} <= set(e)
            if e["ph"] == "X":
                assert "ts" in e and "dur" in e
        tagged = [e for e in evs if e["ph"] == "X"
                  and e.get("args", {}).get("trace_id") == "schema-req"]
        assert any(e["name"] == "schema-span" for e in tagged)
        # the tagged row is named after the trace id
        row = {e["tid"] for e in tagged}
        names = {e["tid"]: e["args"]["name"] for e in evs
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert all("schema-req" in names[t] for t in row)


class TestExportChromeTracingHandler:
    def test_handler_exports_this_sessions_trace(self, tmp_path):
        """export_chrome_tracing was a silent no-op (it only set
        _export_dir); the handler must now write the session's chrome
        trace into dir_name."""
        out = str(tmp_path / "traces")
        prof = profiler.Profiler(
            timer_only=True,
            on_trace_ready=profiler.export_chrome_tracing(
                out, worker_name="w0"))
        with prof:
            with profiler.record_span("handler-span"):
                pass
            prof.step()
        files = os.listdir(out)
        assert files == ["w0.pt_trace.1.json"], files
        raw = open(os.path.join(out, files[0])).read()
        assert "handler-span" in raw
        json.loads(raw)


class TestScheduledCapture:
    def test_full_cycle_with_skip_first_and_repeat(self, tmp_path):
        """scheduler=make_scheduler(...) drives capture windows from
        step(): warmup (READY) spans are excluded, each cycle fires
        on_trace_ready once and exports its own file, and after
        `repeat` cycles the profiler stays CLOSED."""
        out = str(tmp_path / "sched")
        fired = []
        export = profiler.export_chrome_tracing(out, worker_name="w")

        def handler(prof):
            fired.append(prof._step)
            export(prof)

        sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                        repeat=2, skip_first=1)
        prof = profiler.Profiler(timer_only=True, scheduler=sched)
        prof._on_trace_ready = handler
        # states per step i: 0 CLOSED, 1 CLOSED, 2 READY, 3 RECORD,
        # 4 RECORD_AND_RETURN, 5 CLOSED, 6 READY, 7 RECORD,
        # 8 RECORD_AND_RETURN, 9+ CLOSED (repeat exhausted)
        with prof:
            for i in range(10):
                with profiler.record_span(f"sched-span-{i}"):
                    pass
                prof.step()
        assert len(fired) == 2, fired
        assert prof.current_state is profiler.ProfilerState.CLOSED
        files = sorted(os.listdir(out))
        assert files == ["w.pt_trace.1.json", "w.pt_trace.2.json"]
        first = open(os.path.join(out, files[0])).read()
        second = open(os.path.join(out, files[1])).read()
        # window 1 captured exactly steps 3-4; window 2 steps 7-8
        for i in (3, 4):
            assert f"sched-span-{i}" in first
        for i in (0, 1, 2, 5, 6, 7, 8, 9):
            assert f"sched-span-{i}" not in first, i
        for i in (7, 8):
            assert f"sched-span-{i}" in second
        for i in (0, 1, 2, 3, 4, 5, 6, 9):
            assert f"sched-span-{i}" not in second, i

    def test_closed_schedule_records_nothing(self, tmp_path):
        """A scheduler that never reaches RECORD must never fire the
        handler nor capture spans."""
        fired = []
        prof = profiler.Profiler(
            timer_only=True,
            scheduler=lambda step: profiler.ProfilerState.CLOSED,
            on_trace_ready=lambda p: fired.append(1))
        with prof:
            for _ in range(3):
                with profiler.record_span("never-captured"):
                    pass
                prof.step()
        assert not fired
