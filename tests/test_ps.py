"""Parameter-server runtime (distributed/ps_impl.py; reference:
python/paddle/distributed/ps/the_one_ps.py pull/push flow)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.distributed.ps_impl import (
    DistributedEmbedding, EmbeddingPSServer, PSClient, SparseTable,
    _RemoteShard, sparse_embedding_step)


class TestSparseTable:
    def test_pull_deterministic_lazy_init(self):
        a = SparseTable(4, seed=7)
        b = SparseTable(4, seed=7)
        ra = a.pull([3, 100, 3])
        rb = b.pull([100, 3])
        np.testing.assert_array_equal(ra[0], ra[2])          # dup ids
        np.testing.assert_array_equal(ra[1], rb[0])          # same (seed,id)
        assert not np.allclose(SparseTable(4, seed=8).pull([3])[0], ra[0])

    def test_sgd_matches_dense_reference(self):
        t = SparseTable(3, optimizer="sgd", lr=0.5)
        r0 = t.pull([5])[0].copy()
        g = np.asarray([[1.0, -2.0, 0.5]], np.float32)
        t.push([5], g)
        np.testing.assert_allclose(t.pull([5])[0], r0 - 0.5 * g[0],
                                   rtol=1e-6)

    def test_push_sums_duplicate_ids(self):
        """Duplicate ids in one push = scatter-add (dense embedding
        backward), NOT two sequential rule applications."""
        t = SparseTable(2, optimizer="sgd", lr=1.0)
        r0 = t.pull([9])[0].copy()
        g = np.asarray([[1.0, 0.0], [2.0, 1.0]], np.float32)
        t.push([9, 9], g)
        np.testing.assert_allclose(t.pull([9])[0], r0 - g.sum(0), rtol=1e-6)

    def test_adagrad_rule(self):
        t = SparseTable(2, optimizer="adagrad", lr=0.1, eps=1e-8)
        r0 = t.pull([1])[0].copy()
        g1 = np.asarray([[2.0, -1.0]], np.float32)
        t.push([1], g1)
        exp = r0 - 0.1 * g1[0] / (np.sqrt(g1[0] ** 2) + 1e-8)
        np.testing.assert_allclose(t.pull([1])[0], exp, rtol=1e-5)
        g2 = np.asarray([[1.0, 3.0]], np.float32)
        t.push([1], g2)
        acc = g1[0] ** 2 + g2[0] ** 2
        exp2 = exp - 0.1 * g2[0] / (np.sqrt(acc) + 1e-8)
        np.testing.assert_allclose(t.pull([1])[0], exp2, rtol=1e-5)

    def test_adam_rule_matches_reference(self):
        lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
        t = SparseTable(2, optimizer="adam", lr=lr, beta1=b1, beta2=b2,
                        eps=eps)
        row = t.pull([4])[0].copy()
        m = v = np.zeros(2, np.float32)
        for step in range(1, 4):
            g = np.asarray([[0.5 * step, -1.0]], np.float32)
            t.push([4], g)
            m = b1 * m + (1 - b1) * g[0]
            v = b2 * v + (1 - b2) * g[0] ** 2
            mhat = m / (1 - b1 ** step)
            vhat = v / (1 - b2 ** step)
            row = row - lr * mhat / (np.sqrt(vhat) + eps)
        np.testing.assert_allclose(t.pull([4])[0], row, rtol=1e-5)

    def test_state_dict_roundtrip(self):
        t = SparseTable(3, optimizer="adam", lr=0.1)
        t.push([1, 2], np.ones((2, 3), np.float32))
        d = t.state_dict()
        t2 = SparseTable(3, optimizer="adam", lr=0.1)
        t2.load_state_dict(d)
        np.testing.assert_array_equal(t.pull([1, 2]), t2.pull([1, 2]))
        # optimizer state restored too: same next-step update
        g = np.full((2, 3), 0.5, np.float32)
        t.push([1, 2], g)
        t2.push([1, 2], g)
        np.testing.assert_allclose(t.pull([1, 2]), t2.pull([1, 2]),
                                   rtol=1e-6)


class TestPSClient:
    def test_sharded_pull_push_matches_single_shard(self):
        ids = np.asarray([0, 1, 5, 7, 8, 1, 13], np.int64)
        g = np.random.RandomState(0).randn(len(ids), 4).astype(np.float32)
        single = PSClient([SparseTable(4, optimizer="sgd", lr=0.1, seed=3)])
        multi = PSClient([SparseTable(4, optimizer="sgd", lr=0.1, seed=3)
                          for _ in range(3)])
        np.testing.assert_array_equal(single.pull(ids), multi.pull(ids))
        single.push(ids, g)
        multi.push(ids, g)
        np.testing.assert_allclose(single.pull(ids), multi.pull(ids),
                                   rtol=1e-6)

    def test_resharding_preserves_untouched_rows(self):
        """Global-id keying: a different server count reproduces the
        same deterministic init for rows never pushed."""
        a = PSClient([SparseTable(4, seed=5) for _ in range(2)])
        b = PSClient([SparseTable(4, seed=5) for _ in range(4)])
        ids = [2, 3, 11, 17]
        np.testing.assert_array_equal(a.pull(ids), b.pull(ids))


class TestSocketTier:
    def test_remote_matches_inprocess_and_concurrent_push(self):
        srv = EmbeddingPSServer([SparseTable(4, optimizer="sgd", lr=0.1,
                                             seed=1)])
        srv.serve_in_thread()
        try:
            remote = _RemoteShard(srv.endpoint, 0)
            local = SparseTable(4, optimizer="sgd", lr=0.1, seed=1)
            ids = [3, 9, 27]
            np.testing.assert_array_equal(remote.pull(ids), local.pull(ids))
            g = np.ones((3, 4), np.float32)
            remote.push(ids, g)
            local.push(ids, g)
            np.testing.assert_allclose(remote.pull(ids), local.pull(ids),
                                       rtol=1e-6)
            assert len(remote) == 3

            # concurrent pushes from two client threads: same total
            # update for a linear rule (async-SGD determinism on sums)
            import threading
            r2 = _RemoteShard(srv.endpoint, 0)
            gs = np.full((1, 4), 0.5, np.float32)
            ts = [threading.Thread(target=s.push, args=([100], gs))
                  for s in (remote, r2) for _ in range(5)]
            before = remote.pull([100])[0].copy()
            [t.start() for t in ts]
            [t.join() for t in ts]
            np.testing.assert_allclose(
                remote.pull([100])[0], before - 0.1 * 0.5 * 10 * np.ones(4),
                rtol=1e-5)
            r2.close()
            remote.stop_server()
            remote.close()
        finally:
            srv.close()

    def test_multiprocess_server_roundtrip(self):
        """A real server process (fleet-style PT_PS_* env) serving a
        client in this process."""
        code = textwrap.dedent("""
            import os, sys
            sys.path.insert(0, os.environ["REPO"])
            from paddle_tpu.distributed.ps_impl import (SparseTable,
                                                        init_server,
                                                        run_server)
            srv = init_server([SparseTable(2, optimizer="sgd", lr=1.0,
                                           seed=0)], port=0)
            print(srv.endpoint, flush=True)
            run_server()
        """)
        p = subprocess.Popen([sys.executable, "-c", code],
                             env=dict(os.environ, REPO=os.path.dirname(
                                 os.path.dirname(os.path.abspath(__file__))),
                                 JAX_PLATFORMS="cpu"),
                             stdout=subprocess.PIPE, text=True)
        try:
            endpoint = p.stdout.readline().strip()
            assert ":" in endpoint, f"no endpoint from server: {endpoint!r}"
            os.environ["PT_PS_ENDPOINTS"] = endpoint
            from paddle_tpu.distributed.ps_impl import (init_worker,
                                                        stop_worker)
            client = init_worker()
            r0 = client.pull([7])[0].copy()
            client.push([7], np.asarray([[1.0, 2.0]], np.float32))
            np.testing.assert_allclose(client.pull([7])[0],
                                       r0 - [1.0, 2.0], rtol=1e-6)
            stop_worker(stop_servers=True)
            assert p.wait(timeout=10) == 0
        finally:
            os.environ.pop("PT_PS_ENDPOINTS", None)
            if p.poll() is None:
                p.kill()


class TestDistributedEmbedding:
    def test_jit_step_trains_and_matches_dense(self):
        """One sync worker + sgd PS == dense embedding SGD training on
        the same toy regression (exact, modulo float tolerance)."""
        import jax
        import jax.numpy as jnp

        dim, vocab, lr = 4, 32, 0.1
        rng = np.random.RandomState(0)
        ids = rng.randint(0, vocab, (6, 3))
        w = rng.randn(dim).astype(np.float32)
        y = rng.randn(6).astype(np.float32)

        client = PSClient([SparseTable(dim, optimizer="sgd", lr=lr, seed=2)
                           for _ in range(2)])
        emb = DistributedEmbedding(client, dim)

        def loss_fn(gathered, w, y):
            pred = gathered.sum(1) @ w
            return jnp.mean((pred - y) ** 2)

        step = jax.jit(sparse_embedding_step(loss_fn))

        # dense reference: full table, same init, plain SGD on the rows
        dense = np.stack([client.pull([i])[0] for i in range(vocab)])
        losses = []
        for it in range(5):
            rows, inv, uniq = emb.lookup(ids)
            loss, g = step(jnp.asarray(rows), jnp.asarray(inv),
                           jnp.asarray(w), jnp.asarray(y))
            emb.apply_grads(uniq, np.asarray(g))
            losses.append(float(loss))

            def dense_loss(tab):
                return loss_fn(tab[ids.ravel()].reshape(ids.shape + (dim,)),
                               w, y)
            dl, dg = jax.value_and_grad(dense_loss)(jnp.asarray(dense))
            assert abs(dl - loss) < 1e-5
            dense = np.asarray(dense - lr * dg, np.float32)
        assert losses[-1] < losses[0] * 0.9, losses
        np.testing.assert_allclose(
            np.stack([client.pull([i])[0] for i in range(vocab)]),
            dense, atol=1e-5)


class TestCppPSServer:
    """Native shard (csrc/ptps.cpp) behind the same wire protocol."""

    def test_protocol_interop_and_rules(self):
        from paddle_tpu.distributed.ps_impl import CppPSServer
        srv = CppPSServer(4, optimizer="sgd", lr=0.5, seed=3)
        try:
            sh = _RemoteShard(srv.endpoint, 0)
            r0 = sh.pull([5, 9])
            assert r0.shape == (2, 4)
            # deterministic init per (seed, id)
            np.testing.assert_array_equal(sh.pull([5])[0], r0[0])
            g = np.asarray([[1.0, -2.0, 0.5, 0.0]], np.float32)
            sh.push([5], g)
            np.testing.assert_allclose(sh.pull([5])[0], r0[0] - 0.5 * g[0],
                                       rtol=1e-6)
            # duplicate ids scatter-add before the rule
            r9 = sh.pull([9])[0].copy()
            sh.push([9, 9], np.ones((2, 4), np.float32))
            np.testing.assert_allclose(sh.pull([9])[0], r9 - 0.5 * 2.0,
                                       rtol=1e-6)
            assert len(sh) == 2 and len(srv) == 2
            sh.close()
        finally:
            srv.close()

    def test_rejects_nonzero_table_id(self):
        """A C++ server hosts exactly one table; a frame addressed to
        table 1 must be rejected (connection dropped), not silently
        routed into table 0 (ADVICE r4: cross-table corruption)."""
        from paddle_tpu.distributed.ps_impl import CppPSServer
        srv = CppPSServer(4, optimizer="sgd", lr=0.5, seed=3)
        try:
            bad = _RemoteShard(srv.endpoint, 1)
            with pytest.raises((ConnectionError, OSError)):
                bad.pull([5])
            bad.close()
            # table 0 still served, untouched
            ok = _RemoteShard(srv.endpoint, 0)
            assert ok.pull([5]).shape == (1, 4)
            assert len(srv) == 1
            ok.close()
        finally:
            srv.close()

    def test_adam_rule_matches_python_table(self):
        """Same grads on an existing row: the C++ adam update must track
        the Python SparseTable's exactly (init rows differ by design —
        compare the DELTAS)."""
        from paddle_tpu.distributed.ps_impl import CppPSServer
        lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
        srv = CppPSServer(3, optimizer="adam", lr=lr, beta1=b1, beta2=b2,
                          eps=eps, seed=0)
        py = SparseTable(3, optimizer="adam", lr=lr, beta1=b1, beta2=b2,
                         eps=eps, seed=0)
        try:
            sh = _RemoteShard(srv.endpoint, 0)
            c0 = sh.pull([7])[0].copy()
            p0 = py.pull([7])[0].copy()
            for step in range(1, 4):
                g = np.asarray([[0.5 * step, -1.0, 0.25]], np.float32)
                sh.push([7], g)
                py.push([7], g)
            np.testing.assert_allclose(sh.pull([7])[0] - c0,
                                       py.pull([7])[0] - p0, atol=1e-6)
            sh.close()
        finally:
            srv.close()

    def test_sharded_client_mixed_backends(self):
        """PSClient spanning one C++ shard and one Python shard — the
        routing/protocol layer must not care."""
        from paddle_tpu.distributed.ps_impl import (CppPSServer,
                                                    EmbeddingPSServer)
        cpp = CppPSServer(4, optimizer="sgd", lr=0.1, seed=1)
        pysrv = EmbeddingPSServer([SparseTable(4, optimizer="sgd", lr=0.1,
                                               seed=1)])
        pysrv.serve_in_thread()
        try:
            client = PSClient([_RemoteShard(cpp.endpoint, 0),
                               _RemoteShard(pysrv.endpoint, 0)])
            ids = np.asarray([0, 1, 2, 3, 8, 11], np.int64)
            rows = client.pull(ids)
            assert rows.shape == (6, 4)
            g = np.random.RandomState(0).randn(6, 4).astype(np.float32)
            before = rows.copy()
            client.push(ids, g)
            after = client.pull(ids)
            np.testing.assert_allclose(after, before - 0.1 * g, rtol=1e-5)
            for s in client.shards:
                s.close()
        finally:
            cpp.close()
            pysrv.close()

    def test_close_with_open_connection_does_not_hang(self):
        """close() must kick connected clients out of their blocking
        reads instead of dead-waiting on them."""
        import threading
        from paddle_tpu.distributed.ps_impl import CppPSServer
        srv = CppPSServer(4, optimizer="sgd", lr=0.1, seed=0)
        sh = _RemoteShard(srv.endpoint, 0)
        sh.pull([1])                  # connection is live and idle
        done = threading.Event()

        def closer():
            srv.close()
            done.set()
        t = threading.Thread(target=closer, daemon=True)
        t.start()
        assert done.wait(timeout=10), "CppPSServer.close() hung"
        sh.close()
        with pytest.raises(RuntimeError, match="closed"):
            len(srv)

    def test_fleet_backend_cpp_roundtrip(self):
        """init_server(backend='cpp') + run_server in a real process,
        stopped by the client's STOP — the fleet PS flow over libptps."""
        code = textwrap.dedent("""
            import os, sys
            sys.path.insert(0, os.environ["REPO"])
            from paddle_tpu.distributed.ps_impl import (SparseTable,
                                                        init_server,
                                                        run_server)
            srv = init_server([SparseTable(2, optimizer="sgd", lr=1.0,
                                           seed=0)], port=0, backend="cpp")
            print(srv.endpoint, flush=True)
            run_server()
        """)
        p = subprocess.Popen([sys.executable, "-c", code],
                             env=dict(os.environ, REPO=os.path.dirname(
                                 os.path.dirname(os.path.abspath(__file__))),
                                 JAX_PLATFORMS="cpu"),
                             stdout=subprocess.PIPE, text=True)
        try:
            endpoint = p.stdout.readline().strip()
            assert ":" in endpoint, f"no endpoint: {endpoint!r}"
            sh = _RemoteShard(endpoint, 0)
            r0 = sh.pull([3])[0].copy()
            sh.push([3], np.asarray([[1.0, 2.0]], np.float32))
            np.testing.assert_allclose(sh.pull([3])[0], r0 - [1.0, 2.0],
                                       rtol=1e-6)
            sh.stop_server()
            sh.close()
            assert p.wait(timeout=15) == 0
        finally:
            if p.poll() is None:
                p.kill()

    def test_backend_validation(self):
        from paddle_tpu.distributed.ps_impl import init_server
        with pytest.raises(ValueError, match="unknown PS backend"):
            init_server([SparseTable(2)], port=0, backend="rust")
        with pytest.raises(ValueError, match="one table"):
            init_server([SparseTable(2), SparseTable(2)], port=0,
                        backend="cpp")
        t = SparseTable(2)
        t.pull([1])
        with pytest.raises(ValueError, match="materialized"):
            init_server([t], port=0, backend="cpp")


class TestAccessorAndCheckpoint:
    """Feature-entry accessors + table save/load (VERDICT r5 item 5;
    reference: the_one_ps.py table save/load paths, the_one_ps.proto
    CtrAccessor config)."""

    def test_entry_threshold_gates_embedding(self):
        t = SparseTable(4, optimizer="sgd", lr=0.5, seed=1,
                        entry_threshold=3)
        # first two sightings: embedding not created — zeros, grads dropped
        assert np.allclose(t.pull([7]), 0.0)
        t.push([7], np.ones((1, 4), np.float32))
        assert np.allclose(t.pull([7]), 0.0)
        # third sighting crosses the threshold: deterministic init appears
        r = t.pull([7])
        ref = SparseTable(4, optimizer="sgd", lr=0.5, seed=1)
        np.testing.assert_array_equal(r, ref.pull([7]))
        # and training applies now
        t.push([7], np.ones((1, 4), np.float32))
        np.testing.assert_allclose(t.pull([7]), r - 0.5, rtol=1e-6)

    def test_show_decay_and_shrink(self):
        t = SparseTable(4, entry_threshold=2, show_decay_rate=0.5)
        for _ in range(4):
            t.pull([1])          # shows: 4
        t.pull([2])              # shows: 1
        assert len(t) == 2
        t.decay_shows()          # 1 -> 2.0, 2 -> 0.5
        assert t.shrink() == 1   # id 2 dropped
        assert len(t) == 1
        # survivor's row is intact and still addressable
        assert t.pull([1]).shape == (1, 4)

    def test_table_save_load_atomic(self, tmp_path):
        t = SparseTable(4, optimizer="adam", lr=0.1, seed=2)
        t.pull([5, 9, 13])
        t.push([5, 9], np.ones((2, 4), np.float32))
        p = str(tmp_path / "shard.npz")
        t.save(p)
        t2 = SparseTable(4, optimizer="adam", lr=0.1, seed=2)
        t2.load(p)
        np.testing.assert_array_equal(t2.pull([5, 9, 13]), t.pull([5, 9, 13]))
        # adam state carried: same next step on both
        g = np.full((1, 4), 0.3, np.float32)
        t.push([5], g)
        t2.push([5], g)
        np.testing.assert_array_equal(t2.pull([5]), t.pull([5]))

    def test_client_save_load_over_sockets(self, tmp_path):
        # a 2-shard python socket deployment checkpoints server-side,
        # dies, and a FRESH deployment restores to identical state
        srvs = [EmbeddingPSServer([SparseTable(4, optimizer="adagrad",
                                               lr=0.1, seed=s)],
                                  host="127.0.0.1", port=0)
                for s in range(2)]
        for s in srvs:
            s.serve_in_thread()
        cli = PSClient([_RemoteShard(s.endpoint, 0) for s in srvs])
        ids = [3, 8, 11, 14]
        cli.pull(ids)
        cli.push(ids, np.ones((4, 4), np.float32))
        ck = str(tmp_path / "ps_ckpt")
        cli.save(ck)
        before = cli.pull(ids)
        for s in srvs:
            s.close()                      # crash the whole tier

        srvs2 = [EmbeddingPSServer([SparseTable(4, optimizer="adagrad",
                                                lr=0.1, seed=s)],
                                   host="127.0.0.1", port=0)
                 for s in range(2)]
        for s in srvs2:
            s.serve_in_thread()
        cli2 = PSClient([_RemoteShard(s.endpoint, 0) for s in srvs2])
        cli2.load(ck)
        np.testing.assert_array_equal(cli2.pull(ids), before)
        # training continues identically: adagrad state was restored
        cli2.push(ids, np.ones((4, 4), np.float32))
        ref = PSClient([SparseTable(4, optimizer="adagrad", lr=0.1, seed=s)
                        for s in range(2)])
        ref.pull(ids)
        ref.push(ids, np.ones((4, 4), np.float32))
        ref.pull(ids)   # align show counts (pull-counted)
        ref.push(ids, np.ones((4, 4), np.float32))
        np.testing.assert_allclose(cli2.pull(ids), ref.pull(ids), rtol=1e-6)
        for s in srvs2:
            s.close()

    def test_cpp_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.distributed.ps_impl import CppPSServer
        srv = CppPSServer(4, optimizer="adam", lr=0.1, seed=5)
        try:
            sh = _RemoteShard(srv.endpoint, 0)
            sh.pull([2, 6])
            sh.push([2, 6], np.ones((2, 4), np.float32))
            before = sh.pull([2, 6])
            p = str(tmp_path / "cpp_shard.bin")
            sh.save(p)       # over the wire, server-side write
            sh.close()
        finally:
            srv.close()
        srv2 = CppPSServer(4, optimizer="adam", lr=0.1, seed=5)
        try:
            srv2.load(p)     # local (ctypes) restore path
            sh2 = _RemoteShard(srv2.endpoint, 0)
            np.testing.assert_array_equal(sh2.pull([2, 6]), before)
            # adam moments restored: next push matches a never-crashed twin
            sh2.push([2], np.full((1, 4), 0.2, np.float32))
            after = sh2.pull([2])
            sh2.close()
        finally:
            srv2.close()
        twin = CppPSServer(4, optimizer="adam", lr=0.1, seed=5)
        try:
            tw = _RemoteShard(twin.endpoint, 0)
            tw.pull([2, 6])
            tw.push([2, 6], np.ones((2, 4), np.float32))
            tw.push([2], np.full((1, 4), 0.2, np.float32))
            np.testing.assert_allclose(after, tw.pull([2]), rtol=1e-6)
            tw.close()
        finally:
            twin.close()

    def test_async_push_equivalence_after_flush(self):
        ids = np.arange(24, dtype=np.int64)
        g = np.random.RandomState(0).randn(24, 4).astype(np.float32)
        sync = PSClient([SparseTable(4, optimizer="sgd", lr=0.1, seed=s)
                         for s in range(2)])
        asy = PSClient([SparseTable(4, optimizer="sgd", lr=0.1, seed=s)
                        for s in range(2)], async_push=True)
        for c in (sync, asy):
            c.pull(ids)
        for i in range(0, 24, 8):
            sync.push(ids[i:i + 8], g[i:i + 8])
            asy.push(ids[i:i + 8], g[i:i + 8])
        asy.flush()
        np.testing.assert_allclose(asy.pull(ids), sync.pull(ids), rtol=1e-6)


class TestWireHardening:
    """Protocol-error paths: malformed frames and mismatched
    checkpoints must drop cleanly, never corrupt or kill the server."""

    def test_oversize_and_short_push_frames_dropped(self):
        import socket as _socket
        import struct as _struct
        from paddle_tpu.distributed.ps_impl import _HDR
        srv = EmbeddingPSServer([SparseTable(4)], host="127.0.0.1", port=0)
        srv.serve_in_thread()
        try:
            host, port = srv.endpoint.rsplit(":", 1)
            # 4 GiB length field: connection dropped before allocation
            s = _socket.create_connection((host, int(port)))
            s.sendall(_HDR.pack(1, 0, 2, 0) + _struct.pack("<I", 0xFFFFFFFF))
            assert s.recv(1) == b""     # server closed on us
            s.close()
            # push with fewer grad rows than ids: dropped, no broadcast
            s2 = _socket.create_connection((host, int(port)))
            body = np.asarray([5, 9], np.int64).tobytes() \
                + np.ones((1, 4), np.float32).tobytes()
            s2.sendall(_HDR.pack(2, 0, 2, 4)
                       + _struct.pack("<I", len(body)) + body)
            assert s2.recv(1) == b""
            s2.close()
            # server alive and table untouched by either frame
            sh = _RemoteShard(srv.endpoint, 0)
            assert len(sh) == 0
            sh.close()
        finally:
            srv.close()

    def test_dim_mismatched_push_drops_connection(self):
        srv = EmbeddingPSServer([SparseTable(4)], host="127.0.0.1", port=0)
        srv.serve_in_thread()
        try:
            sh = _RemoteShard(srv.endpoint, 0)
            with pytest.raises((ConnectionError, OSError)):
                sh.push([5], np.ones((1, 2), np.float32))  # dim 2 != 4
            sh.close()
            sh2 = _RemoteShard(srv.endpoint, 0)     # server still serving
            assert sh2.pull([5]).shape == (1, 4)
            sh2.close()
        finally:
            srv.close()

    def test_mismatched_checkpoint_rejected_before_mutation(self, tmp_path):
        t4 = SparseTable(4, optimizer="sgd")
        t4.pull([1])
        p = str(tmp_path / "t4.npz")
        t4.save(p)
        t8 = SparseTable(8, optimizer="sgd")
        with pytest.raises(ValueError, match="dim"):
            t8.load(p)
        assert len(t8) == 0      # nothing materialized
        t_ag = SparseTable(4, optimizer="adagrad")
        with pytest.raises(ValueError, match="optimizer"):
            t_ag.load(p)        # sgd ckpt lacks g2 state
        assert len(t_ag) == 0

    def test_wire_ckpt_confined_to_ckpt_dir(self, tmp_path):
        """With ckpt_dir set, wire SAVE/LOAD outside it is rejected
        (the unauthenticated protocol must not be an arbitrary-file
        write primitive); inside it works."""
        srv = EmbeddingPSServer([SparseTable(4)], host="127.0.0.1",
                                port=0, ckpt_dir=str(tmp_path))
        srv.serve_in_thread()
        try:
            sh = _RemoteShard(srv.endpoint, 0)
            sh.pull([3])
            with pytest.raises((ConnectionError, OSError)):
                sh.save("/tmp/outside_ckpt_dir.npz")
            sh.close()
            sh2 = _RemoteShard(srv.endpoint, 0)
            inside = str(tmp_path / "ok.npz")
            sh2.save(inside)
            assert os.path.exists(inside)
            sh2.close()
        finally:
            srv.close()
        assert not os.path.exists("/tmp/outside_ckpt_dir.npz")
