"""Telemetry pulse plane (ISSUE 15): ring time-series over the metrics
registry, /debug/pulse JSON + SSE exposure, anomaly-triggered capture
bundles, and the satellite hardening that rode along — Prometheus
label-value escaping, /debug query-parsing 400s, process-start-time /
scrape-self-cost gauges, and the ptop / ptdump-bundle renderers.

The acceptance scenario runs over REAL HTTP with the pipelined pump: a
PT_FAULTS-style injected stall must appear as a spike in the pulse
step-time series and land EXACTLY ONE capture bundle whose flight dump
and pulse window both carry the triggering request's trace id — and
PT_SERVE_PULSE=0 must produce token-identical outputs with zero extra
threads.
"""
import importlib.util
import io
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import ServingEngine
from paddle_tpu.serving import (FaultPlan, MetricsRegistry,
                                RequestScheduler, Router, ServingClient,
                                ServingHTTPError, ServingServer,
                                build_replicas)
from paddle_tpu.serving.metrics import EngineMetrics
from paddle_tpu.observability.pulse import (PulsePlane, PulseRing,
                                            PulseSampler,
                                            _windowed_percentile)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PTDUMP = os.path.join(_ROOT, "tools", "ptdump.py")

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)
PAGE = 8


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def _engine(params, faults=None, **kw):
    kw.setdefault("max_seqs", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("use_pallas", False)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(params, CFG, faults=faults, **kw)


def _load_ptop():
    spec = importlib.util.spec_from_file_location(
        "ptop", os.path.join(_ROOT, "tools", "ptop.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# sampler unit: snapshots in, ring series out
# ---------------------------------------------------------------------------
class TestSamplerUnit:
    def test_ring_bounded_and_windowed(self):
        r = PulseRing(4)
        for i in range(10):
            r.append(float(i), i * 10)
        assert len(r) == 4
        assert r.window() == [[6.0, 60], [7.0, 70], [8.0, 80],
                              [9.0, 90]]
        assert r.window(since=8.0) == [[8.0, 80], [9.0, 90]]
        assert r.last() == (9.0, 90)

    def test_gauge_samples_and_counter_rates(self):
        s = PulseSampler(depth=8)
        snap1 = {"g": {"type": "gauge", "value": 2.0},
                 "c": {"type": "counter", "value": 10.0}}
        snap2 = {"g": {"type": "gauge", "value": 3.0},
                 "c": {"type": "counter", "value": 30.0}}
        s.sample(snap1, t=100.0)
        s.sample(snap2, t=102.0)
        out = s.series()
        assert [v for _, v in out["g"]] == [2.0, 3.0]
        # first sample has no delta; the second books (30-10)/2s
        assert [v for _, v in out["c:rate"]] == [10.0]

    def test_counter_reset_clamps_to_zero(self):
        s = PulseSampler(depth=8)
        s.sample({"c": {"type": "counter", "value": 50.0}}, t=0.0)
        s.sample({"c": {"type": "counter", "value": 5.0}}, t=1.0)
        assert [v for _, v in s.series()["c:rate"]] == [0.0]

    def test_histogram_windowed_percentiles_and_carry(self):
        s = PulseSampler(depth=8)
        h1 = {"type": "histogram", "count": 0, "sum": 0.0,
              "buckets": {"0.1": 0, "1": 0, "+Inf": 0}}
        # 10 observations land in (0.1, 1] between t0 and t1
        h2 = {"type": "histogram", "count": 10, "sum": 5.0,
              "buckets": {"0.1": 0, "1": 10, "+Inf": 10}}
        s.sample({"h": h1}, t=0.0)   # first sample: no window yet
        s.sample({"h": h2}, t=1.0)
        s.sample({"h": h2}, t=2.0)   # idle interval: carries forward
        p50 = [v for _, v in s.series()["h:p50"]]
        assert p50[0] == pytest.approx(0.1 + 0.9 * 0.5)
        assert p50[1] == p50[0]      # carried, not zeroed
        assert len(p50) == 2
        assert "h:p99" in s.series()

    def test_windowed_percentile_inf_is_lower_bound(self):
        prev = {"1": 0, "+Inf": 0}
        cur = {"1": 0, "+Inf": 4}    # everything past the last edge
        v, n = _windowed_percentile(prev, cur, 50)
        assert (v, n) == (1.0, 4)
        assert _windowed_percentile(cur, cur, 50) == (None, 0)

    def test_goodput_composite(self):
        s = PulseSampler(depth=8)

        def snap(total, good):
            return {"pt_tokens": {"type": "counter", "value": total},
                    "pt_goodput_tokens": {"type": "counter",
                                          "value": good}}
        s.sample(snap(0, 0), t=0.0)       # idle: no evidence -> 1.0
        s.sample(snap(10, 5), t=1.0)      # half the window was badput
        s.sample(snap(10, 5), t=2.0)      # idle again: carries 0.5
        assert [v for _, v in s.series()["goodput_ratio"]] == \
            [1.0, 0.5, 0.5]

    def test_series_prefix_filter_and_window(self):
        s = PulseSampler(depth=8)
        s.sample({"pt_a": {"type": "gauge", "value": 1.0},
                  "pt_b": {"type": "gauge", "value": 2.0}}, t=100.0)
        s.sample({"pt_a": {"type": "gauge", "value": 3.0},
                  "pt_b": {"type": "gauge", "value": 4.0}}, t=200.0)
        only_a = s.series(signals=["pt_a"], now=200.0)
        assert set(only_a) == {"pt_a"}
        recent = s.series(window=50, now=200.0)
        assert [v for _, v in recent["pt_b"]] == [4.0]


# ---------------------------------------------------------------------------
# satellite: Prometheus label-value escaping + new process gauges
# ---------------------------------------------------------------------------
class TestMetricsSatellites:
    def test_label_values_escaped(self):
        reg = MetricsRegistry()
        c = reg.counter("pt_esc", "escaping regression",
                        labels={"path": 'a"b\\c\nd'})
        c.inc()
        text = reg.render_prometheus()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("pt_esc_total{"))
        # spec: backslash -> \\, quote -> \", newline -> literal \n —
        # and the raw newline must NOT split the exposition line
        assert line == 'pt_esc_total{path="a\\"b\\\\c\\nd"} 1'
        assert "\n".join(text.splitlines()) == text.rstrip("\n")

    def test_escaping_roundtrip_keeps_snapshot_key_stable(self):
        reg = MetricsRegistry()
        reg.counter("pt_esc", "", labels={"k": 'v"1'}).inc(2)
        snap = reg.snapshot()
        key = 'pt_esc{k="v\\"1"}'
        assert key in snap and snap[key]["value"] == 2

    def test_process_start_time_and_scrape_self_gauges(self):
        m = EngineMetrics(MetricsRegistry())
        snap = m.registry.snapshot()
        start = snap["pt_process_start_time_seconds"]
        assert start["type"] == "gauge"
        # a plausible wall-clock stamp: after 2020, not in the future
        assert 1577836800 < start["value"] <= time.time() + 1
        assert snap["pt_scrape_self_seconds"]["type"] == "gauge"
        m.observe_scrape_self(0.25)
        snap = m.registry.snapshot()
        assert snap["pt_scrape_self_seconds"]["value"] == \
            pytest.approx(0.25)


# ---------------------------------------------------------------------------
# plane unit: triggers + capture bundles, no engine, no threads
# ---------------------------------------------------------------------------
def _mk_plane(tmp_path, snaps, info=None, **kw):
    """A thread-less plane over a scripted snapshot sequence."""
    it = iter(snaps)
    kw.setdefault("capture_dir", str(tmp_path))
    kw.setdefault("capture_min_s", 600.0)
    kw.setdefault("interval_s", 0.01)
    return PulsePlane(
        lambda: next(it),
        info_fn=lambda: dict(info or {}),
        recent_fn=lambda n: [{"rid": "r1", "trace_id": "req-t1",
                              "state": "done"}],
        start_thread=False, **kw)


def _ctr(v):
    return {"type": "counter", "value": float(v)}


class TestPlaneTriggersAndBundles:
    def test_stall_trigger_writes_one_tagged_bundle(self, tmp_path):
        snaps = [{"pt_step_anomalies": _ctr(0)},
                 {"pt_step_anomalies": _ctr(1)},
                 {"pt_step_anomalies": _ctr(2)}]
        plane = _mk_plane(tmp_path, snaps,
                          info={"trace_ids": ["req-t1"],
                                "breaker_open": False})
        plane.tick()                 # baseline only, never triggers
        assert plane.triggers["step_stall"] == 0
        plane.tick()                 # delta -> trigger -> bundle
        plane.tick()                 # second delta: rate-limited out
        assert plane.triggers["step_stall"] == 2
        assert len(plane.bundles) == 1
        bdir = plane.bundles[0]
        files = sorted(os.listdir(bdir))
        assert files == ["config.json", "flight.json", "meta.json",
                         "metrics.json", "pulse.json", "requests.json"]
        meta = json.load(open(os.path.join(bdir, "meta.json")))
        assert meta["trigger"] == "step_stall"
        assert meta["trace_ids"] == ["req-t1"]
        # the pulse window is self-describing: it embeds the trigger
        pulse = json.load(open(os.path.join(bdir, "pulse.json")))
        assert pulse["trigger"]["trigger"] == "step_stall"
        assert pulse["trigger"]["trace_ids"] == ["req-t1"]
        reqs = json.load(open(os.path.join(bdir, "requests.json")))
        assert reqs["requests"][0]["trace_id"] == "req-t1"
        cfgdoc = json.load(open(os.path.join(bdir, "config.json")))
        assert cfgdoc["pid"] == os.getpid() and "env" in cfgdoc

    def test_slo_burst_needs_threshold(self, tmp_path):
        snaps = [{"pt_slo_violated{a=\"b\"}": _ctr(0)},
                 {"pt_slo_violated{a=\"b\"}": _ctr(2)},   # < burst
                 {"pt_slo_violated{a=\"b\"}": _ctr(5)}]   # >= burst
        plane = _mk_plane(tmp_path, snaps, slo_burst=3)
        plane.tick()
        plane.tick()
        assert plane.triggers["slo_burst"] == 0
        plane.tick()
        assert plane.triggers["slo_burst"] == 1

    def test_breaker_open_edge_triggers_once(self, tmp_path):
        info = {"breaker_open": False}
        plane = PulsePlane(lambda: {}, info_fn=lambda: dict(info),
                           capture_dir=str(tmp_path),
                           interval_s=0.01, start_thread=False)
        plane.tick()
        info["breaker_open"] = True
        plane.tick()                 # False -> True edge
        plane.tick()                 # still True: no re-trigger
        assert plane.triggers["breaker_open"] == 1

    def test_trigger_accounting_runs_under_the_plane_lock(self):
        """Regression (found by tpuracer's TPL008 pass): the counter-
        delta pass and the `triggers[trig] += 1` read-modify-write used
        to run OUTSIDE self._lock, so the pulse daemon racing an
        opportunistic scrape tick could lose fires. Pin the fix: every
        trigger-dict write happens with the lock held."""
        plane = PulsePlane(lambda: {}, interval_s=3600.0,
                           start_thread=False)
        locked_writes = []

        class Guarded(dict):
            def __setitem__(self, key, value):
                locked_writes.append(plane._lock.locked())
                super().__setitem__(key, value)

        plane.triggers = Guarded(plane.triggers)
        plane._check_triggers({"pt_step_anomalies": _ctr(0)})  # baseline
        plane._check_triggers({"pt_step_anomalies": _ctr(2)})
        assert plane.triggers["step_stall"] == 1
        assert locked_writes == [True]

    def test_payload_triggers_are_a_snapshot(self, tmp_path):
        plane = _mk_plane(tmp_path, [{}])
        doc = plane.payload()
        doc["triggers"]["step_stall"] = 99
        doc["bundles"].append("bogus")
        assert plane.triggers["step_stall"] == 0
        assert plane.bundles == []

    def test_no_capture_dir_means_no_bundles(self, tmp_path):
        snaps = [{"pt_engine_restarts": _ctr(0)},
                 {"pt_engine_restarts": _ctr(1)}]
        plane = _mk_plane(tmp_path, snaps, capture_dir=None)
        plane.capture_dir = None
        plane.tick()
        plane.tick()
        assert plane.triggers["engine_restart"] == 1
        assert plane.bundles == []

    def test_capture_max_bounds_bundle_count(self, tmp_path):
        n = 5
        snaps = [{"pt_step_anomalies": _ctr(i)} for i in range(n + 1)]
        plane = _mk_plane(tmp_path, snaps, capture_max=2,
                          capture_min_s=0.0)
        for _ in range(n + 1):
            plane.tick()
        assert plane.triggers["step_stall"] == n
        assert len(plane.bundles) == 2

    def test_ptdump_renders_bundle_narrative(self, tmp_path):
        snaps = [{"pt_step_anomalies": _ctr(0),
                  "pt_serving_step_seconds": {
                      "type": "histogram", "count": 0, "sum": 0.0,
                      "buckets": {"0.1": 0, "+Inf": 0}}},
                 {"pt_step_anomalies": _ctr(1),
                  "pt_serving_step_seconds": {
                      "type": "histogram", "count": 3, "sum": 0.9,
                      "buckets": {"0.1": 0, "+Inf": 3}}}]
        plane = _mk_plane(tmp_path, snaps,
                          info={"trace_ids": ["req-t1"]})
        plane.tick()
        plane.tick()
        [bdir] = plane.bundles
        for argv in ([PTDUMP, "bundle", bdir], [PTDUMP, bdir]):
            proc = subprocess.run([sys.executable, *argv],
                                  capture_output=True, text=True,
                                  timeout=60)
            assert proc.returncode == 0, proc.stderr
            assert "capture bundle" in proc.stdout
            assert "trigger: step_stall" in proc.stdout
            assert "req-t1" in proc.stdout
            assert "flight recorder dump" in proc.stdout

    def test_ptop_renders_recorded_payload(self, tmp_path):
        snaps = [{"pt_q": {"type": "gauge", "value": float(i)},
                  "pt_step_anomalies": _ctr(0)} for i in range(6)]
        plane = _mk_plane(tmp_path, snaps)
        for _ in range(6):
            plane.tick()
        f = tmp_path / "pulse.json"
        f.write_text(json.dumps(plane.payload()))
        ptop = _load_ptop()
        out = io.StringIO()
        rc = ptop.main(["--file", str(f), "--once", "--no-color"],
                       out=out)
        text = out.getvalue()
        assert rc == 0
        assert "pt_q" in text and "pt_step_anomalies:rate" in text
        assert any(ch in text for ch in ptop.BARS)

    def test_ptop_renders_router_columns_and_highlights(self):
        ptop = _load_ptop()
        mk = lambda anom: {
            "enabled": True, "interval_s": 1.0,
            "signals": {"pt_serving_queue_depth": [[1.0, 2], [2.0, 3]],
                        "pt_step_anomalies:rate": [[2.0, anom]]},
            "triggers": {"step_stall": int(anom)}, "bundles": []}
        out = io.StringIO()
        ptop.render({"enabled": True,
                     "replicas": {"r0": mk(0), "r1": mk(1)}}, out=out)
        text = out.getvalue()
        assert "r0" in text and "r1" in text
        assert "pt_serving_queue_depth" in text
        assert "triggers step_stall=1" in text
        out = io.StringIO()
        ptop.render({"enabled": False}, out=out)
        assert "disabled" in out.getvalue()


# ---------------------------------------------------------------------------
# HTTP: /debug hardening (400s, never 500s) + pulse exposure
# ---------------------------------------------------------------------------
class TestDebugEndpoints:
    @pytest.fixture()
    def served(self, params, monkeypatch, tmp_path):
        monkeypatch.setenv("PT_PULSE_INTERVAL_S", "0.05")
        monkeypatch.setenv("PT_CAPTURE_DIR", str(tmp_path / "caps"))
        monkeypatch.delenv("PT_SERVE_PULSE", raising=False)
        sched = RequestScheduler(_engine(params), max_queue=8,
                                 metrics=MetricsRegistry())
        srv = ServingServer(sched, port=0).start()
        yield srv, sched, ServingClient(port=srv.port)
        srv.stop(drain=False, timeout=30)

    def _get(self, srv, path):
        conn = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=30)
        return conn.status, json.loads(conn.read().decode())

    def test_bad_query_values_are_400_not_500(self, served):
        srv, _, cl = served
        for path in ("/debug/requests?last=abc",
                     "/debug/requests?last=1.5",
                     "/debug/flightrecorder?dump=yes",
                     "/debug/pulse?window=abc",
                     "/debug/pulse?count=x&stream=1"):
            with pytest.raises(ServingHTTPError) as ei:
                cl._json_call("GET", path)
            assert ei.value.status == 400, path
            assert "bad request" in str(ei.value), path

    def test_good_queries_still_work(self, served):
        srv, _, cl = served
        cl.complete([1, 2, 3], max_tokens=2)
        assert cl.debug_requests(last=5)["requests"]
        st, doc = self._get(srv, "/debug/flightrecorder?dump=0")
        assert st == 200 and "events" in doc

    def test_debug_pulse_json_and_filter(self, served):
        srv, sched, cl = served
        cl.complete([1, 2, 3], max_tokens=4)
        sched._pulse.tick()
        doc = cl.debug_pulse()
        assert doc["enabled"] is True
        assert doc["interval_s"] == pytest.approx(0.05)
        assert any(k.startswith("pt_serving_queue_depth")
                   for k in doc["signals"])
        only = cl.debug_pulse(signals=["goodput_ratio"])
        assert set(only["signals"]) == {"goodput_ratio"}

    def test_pulse_sse_stream_bounded_by_count(self, served):
        srv, _, cl = served
        cl.complete([1, 2, 3], max_tokens=2)
        events = []
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/pulse?stream=1&count=2",
            timeout=30)
        assert resp.headers["Content-Type"] == "text/event-stream"
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: "):
                events.append(json.loads(line[len("data: "):]))
        assert len(events) == 2
        assert all(e["enabled"] for e in events)

    def test_metrics_scrape_rides_sampling(self, served):
        srv, sched, cl = served
        cl.complete([1, 2, 3], max_tokens=2)
        time.sleep(0.06)            # let the dedup interval lapse
        text = cl.metrics_text()
        assert "pt_process_start_time_seconds" in text
        assert "pt_scrape_self_seconds" in text
        assert 'pt_serving_slots{kind="decode"}' in text
        assert 'pt_serving_queue_depth_priority{priority="normal"}' \
            in text
        assert len(sched._pulse.sampler.series()) > 0


# ---------------------------------------------------------------------------
# the acceptance: stall over real HTTP -> spike + one tagged bundle
# ---------------------------------------------------------------------------
class TestStallCaptureE2E:
    def test_injected_stall_spikes_and_bundles(self, params,
                                               monkeypatch, tmp_path):
        cap = tmp_path / "caps"
        monkeypatch.setenv("PT_SERVE_PULSE", "1")
        monkeypatch.setenv("PT_PULSE_INTERVAL_S", "0.05")
        monkeypatch.setenv("PT_CAPTURE_DIR", str(cap))
        monkeypatch.setenv("PT_CAPTURE_MIN_S", "600")
        # the drill: one device-step launch delayed 0.5s, well past
        # the sentinel's band, after its 20-step warmup has settled
        sched = RequestScheduler(
            _engine(params, faults=FaultPlan(
                "step_launch:delay@30:delay=0.5")),
            max_queue=8, metrics=MetricsRegistry(), pipeline=True)
        srv = ServingServer(sched, port=0).start()
        try:
            cl = ServingClient(port=srv.port, timeout=300)
            r = cl.complete([1, 5, 9], max_tokens=60)
            trace_id = r["trace_id"]
            assert trace_id and len(r["tokens"]) == 60
            # deterministic close: drain the sentinel + judge triggers
            sched._pulse.tick()
            payload = cl.debug_pulse()
        finally:
            srv.stop(drain=False, timeout=60)

        # the stall is visible in the ring: p99 spikes over the median
        series = payload["signals"]["pt_serving_step_seconds:p99"]
        vals = [v for _, v in series if v]
        assert max(vals) >= 0.5, series
        assert max(vals) > 3 * sorted(vals)[len(vals) // 2]
        assert payload["triggers"]["step_stall"] >= 1

        # exactly one bundle (rate limit), tagged with the trace id
        bundles = sorted(cap.iterdir())
        assert len(bundles) == 1, bundles
        bdir = str(bundles[0])
        assert "step_stall" in os.path.basename(bdir)
        pulse = json.load(open(os.path.join(bdir, "pulse.json")))
        assert trace_id in pulse["trigger"]["trace_ids"]
        flight_text = open(os.path.join(bdir, "flight.json")).read()
        assert trace_id in flight_text
        assert "anomaly.step_stall" in flight_text

        # both tools render the drill's artifacts
        ptop = _load_ptop()
        f = tmp_path / "pulse.json"
        f.write_text(json.dumps(payload))
        out = io.StringIO()
        assert ptop.main(["--file", str(f), "--once", "--no-color"],
                         out=out) == 0
        assert "pt_serving_step_seconds:p99" in out.getvalue()
        assert "triggers step_stall=" in out.getvalue()
        proc = subprocess.run(
            [sys.executable, PTDUMP, "bundle", bdir],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "trigger: step_stall" in proc.stdout
        assert trace_id in proc.stdout

    def test_pulse_off_is_token_identical_and_threadless(
            self, params, monkeypatch):
        prompt, kw = [2, 7, 11], {"max_new_tokens": 12}

        def run():
            sched = RequestScheduler(_engine(params), max_queue=4,
                                     metrics=MetricsRegistry())
            plane = sched._pulse
            try:
                return sched.submit(prompt, **kw).result(timeout=600), \
                    plane
            finally:
                sched.shutdown(drain=True, timeout=60)

        monkeypatch.setenv("PT_SERVE_PULSE", "1")
        on_tokens, on_plane = run()
        assert on_plane is not None and not on_plane.thread_alive

        monkeypatch.setenv("PT_SERVE_PULSE", "0")
        before = {t.name for t in threading.enumerate()}
        off_tokens, off_plane = run()
        after = {t.name for t in threading.enumerate()}
        assert off_plane is None
        assert not any(n.startswith("pt-pulse") for n in after - before)
        assert off_tokens == on_tokens      # token-identical

    def test_pulse_off_debug_endpoint_says_disabled(self, params,
                                                    monkeypatch):
        monkeypatch.setenv("PT_SERVE_PULSE", "0")
        sched = RequestScheduler(_engine(params), max_queue=4,
                                 metrics=MetricsRegistry())
        srv = ServingServer(sched, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            assert cl.debug_pulse() == {"enabled": False}
        finally:
            srv.stop(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# router aggregation: one payload per replica, TPL004-clean
# ---------------------------------------------------------------------------
class TestRouterPulse:
    def test_router_aggregates_per_replica(self, params, monkeypatch):
        monkeypatch.setenv("PT_SERVE_PULSE", "1")
        monkeypatch.setenv("PT_PULSE_INTERVAL_S", "0.05")
        monkeypatch.delenv("PT_CAPTURE_DIR", raising=False)
        reps = build_replicas(lambda i: _engine(params), 2,
                              max_queue=8)
        router = Router(reps)
        srv = ServingServer(router, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            cl.complete([1, 2, 3], max_tokens=2)
            for rep in reps:
                rep.scheduler._pulse.tick()
            doc = cl.debug_pulse()
            assert doc["enabled"] is True
            assert set(doc["replicas"]) == \
                {r.replica_id for r in reps}
            for rid, p in doc["replicas"].items():
                assert p["enabled"] and p["signals"], rid
        finally:
            srv.stop(drain=False, timeout=30)
