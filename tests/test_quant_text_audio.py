"""quantization / text / audio / flops / onnx-stablehlo tests."""
import numpy as np
import pytest

import paddle_tpu as pt


class TestQuantization:
    def test_weight_quant_roundtrip(self):
        w = pt.randn([32, 16])
        q, scale = pt.quantization.weight_quantize(w)
        assert q.dtype == np.int8
        deq = pt.quantization.weight_dequantize(q, scale)
        err = np.abs(deq.numpy() - w.numpy()).max()
        assert err < np.abs(w.numpy()).max() / 100

    def test_weight_only_linear_close_to_fp(self):
        x = pt.randn([4, 32])
        lin = pt.nn.Linear(32, 8)
        ref = lin(x).numpy()
        q, s = pt.quantization.weight_quantize(lin.weight)
        out = pt.quantization.weight_only_linear(x, q, lin.bias, s).numpy()
        assert np.abs(out - ref).max() < 0.05 * np.abs(ref).max() + 0.05

    def test_ptq_model(self):
        net = pt.nn.Sequential(pt.nn.Linear(8, 16), pt.nn.ReLU(),
                               pt.nn.Linear(16, 4))
        x = pt.randn([2, 8])
        ref = net(x).numpy()
        pt.quantization.PTQ().quantize(net)
        out = net(x).numpy()
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.1

    def test_qat_train_convert_accuracy(self):
        """VERDICT r1 item 6 (reference quantization/qat.py:27): QAT fake-
        quant trains a net, convert() yields int8 weight-only layers whose
        eval accuracy matches fp32 within tolerance."""
        from paddle_tpu.quantization import QAT, QuantConfig, QuantizedLinear
        pt.seed(5)
        rng = np.random.RandomState(5)
        # separable 3-class problem
        centers = rng.randn(3, 8) * 3
        xs = np.concatenate([centers[i] + rng.randn(40, 8) * 0.5
                             for i in range(3)]).astype(np.float32)
        ys = np.repeat(np.arange(3), 40)

        def build():
            pt.seed(6)
            return pt.nn.Sequential(pt.nn.Linear(8, 32), pt.nn.ReLU(),
                                    pt.nn.Linear(32, 3))

        def train(net, steps=60):
            opt = pt.optimizer.Adam(5e-2, parameters=net.parameters())
            for _ in range(steps):
                loss = pt.nn.functional.cross_entropy(
                    net(pt.to_tensor(xs)), pt.to_tensor(ys))
                loss.backward()
                opt.step()
                opt.clear_grad()
            return net

        def acc(net):
            pred = np.argmax(net(pt.to_tensor(xs)).numpy(), -1)
            return float((pred == ys).mean())

        fp32 = train(build())
        acc_fp32 = acc(fp32)
        assert acc_fp32 > 0.9

        qat = QAT(QuantConfig())
        net = qat.quantize(build())
        # fake-quant forward actually quantizes: output lies on the grid
        train(net)
        acc_qat = acc(net)
        net_int8 = qat.convert(net)
        assert isinstance(net_int8[0], QuantizedLinear)
        assert isinstance(net_int8[2], QuantizedLinear)
        assert net_int8[0].quant_weight.numpy().dtype == np.int8
        acc_int8 = acc(net_int8)
        assert acc_qat > 0.9
        assert abs(acc_int8 - acc_fp32) < 0.05, (acc_int8, acc_fp32)

    def test_qat_fake_quant_grid_and_ste(self):
        from paddle_tpu.quantization import (FakeQuanterChannelWiseAbsMax,
                                             QAT, QuantConfig)
        import jax.numpy as jnp
        wq = FakeQuanterChannelWiseAbsMax()
        w = pt.to_tensor(np.random.RandomState(0).randn(4, 6).astype(np.float32),
                         stop_gradient=False)
        fq = wq(w._value)
        scale = np.abs(np.asarray(w._value)).max(0, keepdims=True) / 127.0
        grid = np.round(np.asarray(w._value) / scale)
        assert np.allclose(np.asarray(fq), grid * scale, atol=1e-6)
        # STE: gradient of sum(fake_quant(w)) wrt w is ~1 everywhere
        import jax
        g = jax.grad(lambda x: jnp.sum(wq(x)))(w._value)
        assert np.allclose(np.asarray(g), 1.0)

    def test_quantized_linear_layer(self):
        lin = pt.nn.Linear(8, 4)
        qlin = pt.quantization.QuantizedLinear.from_linear(lin)
        x = pt.randn([2, 8])
        assert np.abs(qlin(x).numpy() - lin(x).numpy()).max() < 0.1


class TestText:
    def test_byte_tokenizer_roundtrip(self):
        tok = pt.text.ByteTokenizer()
        ids = tok.encode("hello tpu", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_token_id and ids[-1] == tok.eos_token_id
        assert tok.decode(ids) == "hello tpu"

    def test_tokenizer_padding(self):
        tok = pt.text.ByteTokenizer()
        out = tok(["ab", "abcd"], padding=True)
        assert out["input_ids"].shape == (2, 4)
        assert out["attention_mask"].sum() == 6

    def test_lm_dataset(self):
        ds = pt.text.LMDataset(np.arange(100), seq_len=10)
        x, y = ds[0]
        assert np.array_equal(y, x + 1)

    def test_imdb_uci(self):
        ds = pt.text.Imdb(mode="train")
        ids, label = ds[0]
        assert label in (0, 1)
        uci = pt.text.UCIHousing(mode="test")
        x, y = uci[0]
        assert x.shape == (13,)


class TestBPE:
    CORPUS = ["the quick brown fox jumps over the lazy dog",
              "the lazy dog sleeps", "quick quick brown fox the the the",
              "pack my box with five dozen liquor jugs"] * 3

    def test_train_and_roundtrip(self):
        from paddle_tpu.text import BPETokenizer
        tok = BPETokenizer.train(self.CORPUS, vocab_size=300)
        assert tok.vocab_size <= 300
        s = "the quick lazy fox"
        ids = tok.encode(s)
        assert tok.decode(ids) == s
        # merges actually compress vs raw bytes
        assert len(ids) < len(s.encode())

    def test_native_matches_python(self):
        from paddle_tpu.text import BPETokenizer
        tok = BPETokenizer.train(self.CORPUS, vocab_size=300)
        if tok._native is None:
            import pytest as _pt
            _pt.skip("native lib unavailable")
        for s in self.CORPUS + ["unseen zebra text!", "", "a",
                                "ünïcodé ⚡ bytes"]:
            native = tok.encode(s)
            python = tok._encode_python(s.encode("utf-8"))
            assert native == python, s
            assert tok.decode(native) == s

    def test_save_load(self, tmp_path):
        from paddle_tpu.text import BPETokenizer
        tok = BPETokenizer.train(self.CORPUS, vocab_size=280)
        p = str(tmp_path / "tok.json")
        tok.save(p)
        tok2 = BPETokenizer.from_files(p)
        s = "the quick brown dog"
        assert tok2.encode(s) == tok.encode(s)
        assert tok2.vocab_size == tok.vocab_size

    def test_padding_batch(self):
        from paddle_tpu.text import BPETokenizer
        tok = BPETokenizer.train(self.CORPUS, vocab_size=280)
        out = tok(["the dog", "the quick brown fox jumps"], padding=True)
        ids, mask = out["input_ids"], out["attention_mask"]
        assert ids.shape == mask.shape and (ids[mask == 0] ==
                                            tok.pad_token_id).all()

    def test_bos_eos(self):
        from paddle_tpu.text import BPETokenizer
        tok = BPETokenizer.train(self.CORPUS, vocab_size=270)
        ids = tok.encode("fox", add_bos=True, add_eos=True)
        assert ids[0] == tok.bos_token_id and ids[-1] == tok.eos_token_id
        assert tok.decode(ids) == "fox"


class TestAudio:
    def test_spectrogram_shapes(self):
        wav = pt.randn([1, 4000])
        spec = pt.audio.features.Spectrogram(n_fft=256, hop_length=128)(wav)
        assert spec.shape[1] == 129  # n_fft//2+1

    def test_mel_and_mfcc(self):
        wav = pt.randn([1, 4000])
        mel = pt.audio.features.LogMelSpectrogram(sr=16000, n_fft=256,
                                                  n_mels=32)(wav)
        assert mel.shape[1] == 32
        mfcc = pt.audio.features.MFCC(sr=16000, n_mfcc=13, n_fft=256,
                                      n_mels=32)(wav)
        assert mfcc.shape[1] == 13

    def test_parseval_energy(self):
        # rect window, hop=n_fft → frame energies match (Parseval)
        wav_np = np.random.randn(1, 1024).astype(np.float32)
        spec = pt.audio.functional.spectrogram(
            pt.to_tensor(wav_np), 256, 256,
            pt.audio.functional.get_window("rect", 256), power=2.0,
            center=False)
        frame0 = wav_np[0, :256]
        e_time = (frame0 ** 2).sum()
        s = spec.numpy()[0, :, 0]
        e_freq = (s[0] + 2 * s[1:-1].sum() + s[-1]) / 256
        assert np.allclose(e_time, e_freq, rtol=1e-3)


class TestFlops:
    def test_lenet_flops(self):
        net = pt.vision.models.LeNet()
        macs = pt.flops(net, (1, 1, 28, 28))
        assert 300_000 < macs < 600_000  # LeNet ≈ 0.42 MMACs


class TestStableHLOExport:
    def test_export_and_run(self, tmp_path):
        net = pt.nn.Linear(4, 2)
        x = pt.randn([1, 4])
        path = str(tmp_path / "m.stablehlo")
        pt.onnx.export_stablehlo(net, path, [x])
        exported = pt.onnx.load_stablehlo(path)
        params, _ = net.functional_state()
        out = exported.call(params, x._value)
        assert np.allclose(np.asarray(out), net(x).numpy(), atol=1e-6)


class TestSignalGeometric:
    def test_stft_istft_roundtrip(self):
        wav = np.random.randn(2, 2048).astype(np.float32)
        win = pt.audio.functional.get_window("hann", 256)
        spec = pt.signal.stft(pt.to_tensor(wav), 256, 64, window=pt.Tensor(win))
        rec = pt.signal.istft(spec, 256, 64, window=pt.Tensor(win),
                              length=2048)
        assert np.allclose(rec.numpy(), wav, atol=1e-4)

    def test_frame_overlap_add(self):
        x = pt.to_tensor(np.arange(10, dtype=np.float32))
        f = pt.signal.frame(x, 4, 2)
        assert f.shape == [4, 4]
        back = pt.signal.overlap_add(f, 2)
        # interior elements are double-counted by OLA with hop 2, frame 4
        assert back.shape == [10]

    def test_send_u_recv(self):
        x = pt.to_tensor(np.array([[1.0], [2.0], [3.0]]))
        src = pt.to_tensor(np.array([0, 1, 2, 0]))
        dst = pt.to_tensor(np.array([1, 2, 1, 0]))
        out = pt.geometric.send_u_recv(x, src, dst, "sum")
        assert out.numpy().tolist() == [[1.0], [4.0], [2.0]]

    def test_segment_ops(self):
        data = pt.to_tensor(np.array([1.0, 2.0, 3.0, 4.0]))
        ids = pt.to_tensor(np.array([0, 0, 1, 1]))
        assert pt.geometric.segment_sum(data, ids).numpy().tolist() == [3.0, 7.0]
        assert pt.geometric.segment_mean(data, ids).numpy().tolist() == [1.5, 3.5]
        assert pt.geometric.segment_max(data, ids).numpy().tolist() == [2.0, 4.0]


class TestQATInplaceContract:
    def test_quantize_does_not_mutate_original(self):
        from paddle_tpu.quantization import QAT, QuantConfig
        pt.seed(9)
        net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.ReLU(),
                               pt.nn.Linear(8, 2))
        x = pt.randn([3, 4])
        ref = net(x).numpy()
        qnet = QAT(QuantConfig()).quantize(net)  # inplace=False default
        assert qnet is not net
        # original still computes exact fp32 math
        assert np.allclose(net(x).numpy(), ref, atol=0)
        # the copy computes fake-quantized (different) math
        assert not np.allclose(qnet(x).numpy(), ref, atol=1e-7)
