"""Unified ragged step (ISSUE 11): ONE jitted `unified_step` serves an
arbitrary mix of prefill chunks, suffix prefills, spec-verify grids and
decodes from a flat token buffer. Acceptance asserted here:

  * the pallas ragged-paged-attention kernel (interpret mode) is
    BIT-identical to the pure-jnp reference on CPU, fp32 and int8;
  * ragged engines are token-identical to the bucketed entry points
    across every mode (plain / int8 / prefix / tier / spec / chunked /
    preemption), under both the sync and the pipelined pump;
  * changing the prefill/decode mix between waves triggers ZERO
    retraces of `serving.unified_step`;
  * pad-waste telemetry: a ragged run books no `pt_pad_tokens` and a
    growing `pt_ragged_tokens`; the bucketed run pads;
  * a PT_FAULTS `step_launch` crash mid-run warm-restarts, requeues,
    and still yields token-identical outputs through the scheduler.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.kernels import (ragged_paged_attention,
                                ragged_paged_attention_reference)
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import Request, ServingEngine
from paddle_tpu.serving.metrics import MetricsRegistry
from paddle_tpu.serving.scheduler import RequestScheduler

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Kernel vs reference: bit-identical on CPU (interpret mode)
# ---------------------------------------------------------------------------
class TestKernelBitEquivalence:
    PAGE = 8
    KVH = 2
    QH = 4
    D = 16
    PAGES_PER_SEQ = 4
    NUM_PAGES = 12
    SLOTS = 3

    def _problem(self, seed=0, quant=False):
        rng = np.random.default_rng(seed)
        q = rng.standard_normal((10, self.QH, self.D)).astype(np.float32)
        shape = (self.KVH, self.NUM_PAGES, self.PAGE, self.D)
        if quant:
            k_pages = rng.integers(-127, 128, shape).astype(np.int8)
            v_pages = rng.integers(-127, 128, shape).astype(np.int8)
            ks = rng.uniform(0.01, 0.1, shape[:3] + (1,)).astype(np.float32)
            vs = rng.uniform(0.01, 0.1, shape[:3] + (1,)).astype(np.float32)
        else:
            k_pages = rng.standard_normal(shape).astype(np.float32)
            v_pages = rng.standard_normal(shape).astype(np.float32)
            ks = vs = None
        ptab = rng.permutation(self.NUM_PAGES)[
            :self.SLOTS * self.PAGES_PER_SEQ].reshape(
            self.SLOTS, self.PAGES_PER_SEQ).astype(np.int32)
        # the mix: a 5-token prefill run on slot 0, two decodes, and
        # three inactive slack rows (pos -1) — one wave, one call
        tok_slot = np.array([0, 0, 0, 0, 0, 1, 2, 0, 0, 0], np.int32)
        tok_pos = np.array([0, 1, 2, 3, 4, 15, 9, -1, -1, -1], np.int32)
        return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
                jnp.asarray(ptab), jnp.asarray(tok_slot),
                jnp.asarray(tok_pos), ks, vs)

    @pytest.mark.parametrize("quant", [False, True],
                             ids=["fp32", "int8"])
    def test_pallas_interpret_bit_identical(self, quant):
        q, k, v, ptab, slot, pos, ks, vs = self._problem(quant=quant)
        kw = {}
        if quant:
            kw = {"k_scale": jnp.asarray(ks), "v_scale": jnp.asarray(vs)}
        ref = ragged_paged_attention(q, k, v, ptab, slot, pos,
                                     use_pallas=False, **kw)
        ker = ragged_paged_attention(q, k, v, ptab, slot, pos,
                                     use_pallas=True, interpret=True, **kw)
        ref = np.asarray(ref)
        ker = np.asarray(ker)
        assert ref.shape == ker.shape == (10, self.QH, self.D)
        # BIT-identical, not allclose: the engine swaps implementations
        # by backend and the sampled token stream must not notice
        assert np.array_equal(ref, ker), \
            f"max |delta| = {np.abs(ref - ker).max()}"
        # inactive slack rows (pos -1) produce exact zeros
        assert not ref[:7].any() == ref[7:].any()
        assert np.array_equal(ref[7:], np.zeros_like(ref[7:]))

    def test_reference_entry_point_is_the_dispatch_target(self):
        """CPU default (use_pallas unset, no TPU) must route to the
        reference — tier-1 never imports a TPU-only path."""
        q, k, v, ptab, slot, pos, _, _ = self._problem()
        via_dispatch = ragged_paged_attention(q, k, v, ptab, slot, pos)
        direct = ragged_paged_attention_reference(q, k, v, ptab, slot, pos)
        assert np.array_equal(np.asarray(via_dispatch), np.asarray(direct))

    def test_causality_prefill_rows_ignore_future(self):
        """Row at pos p must see exactly columns <= p: rerunning with
        later-position KV overwritten cannot change earlier rows."""
        q, k, v, ptab, slot, pos, _, _ = self._problem()
        base = np.asarray(ragged_paged_attention(q, k, v, ptab, slot, pos))
        k2 = np.asarray(k).copy()
        v2 = np.asarray(v).copy()
        # clobber slot 0's column 4 (page ord 0, offset 4): only the
        # prefill row AT pos 4 may change, rows 0..3 must not
        pg = int(np.asarray(ptab)[0, 0])
        k2[:, pg, 4] = 99.0
        v2[:, pg, 4] = -99.0
        out = np.asarray(ragged_paged_attention(
            q, jnp.asarray(k2), jnp.asarray(v2), ptab, slot, pos))
        assert np.array_equal(base[:4], out[:4])
        assert not np.array_equal(base[4], out[4])


# ---------------------------------------------------------------------------
# Tunable kernel tiling (ISSUE 12): a tile choice never changes a bit
# ---------------------------------------------------------------------------
class TestKernelTiling:
    """`block_q` x `block_pages` is a STATIC tuning knob: every legal
    tile must be BIT-identical to the seed tile, fp32 and int8 — the
    autotuner (tools/tune_ragged.py) may pick any of them and the
    sampled token stream must not notice."""
    # the test problem's GQA group (4q/2kv -> 2) pads to the sublane
    # minimum 8; PAGES_PER_SEQ=4 bounds block_pages
    TILES = [(8, 2), (16, 1), (16, 4)]

    @pytest.mark.parametrize("quant", [False, True], ids=["fp32", "int8"])
    def test_every_legal_tile_bit_identical(self, quant):
        prob = TestKernelBitEquivalence()
        q, k, v, ptab, slot, pos, ks, vs = prob._problem(quant=quant)
        kw = {}
        if quant:
            kw = {"k_scale": jnp.asarray(ks), "v_scale": jnp.asarray(vs)}
        base = np.asarray(ragged_paged_attention(
            q, k, v, ptab, slot, pos, use_pallas=True, interpret=True,
            **kw))
        for bq, bp in self.TILES:
            out = np.asarray(ragged_paged_attention(
                q, k, v, ptab, slot, pos, use_pallas=True, interpret=True,
                block_q=bq, block_pages=bp, **kw))
            assert np.array_equal(base, out), \
                f"tile (block_q={bq}, block_pages={bp}) diverged"

    def test_reference_honors_block_q_too(self):
        """use_pallas=False with a tuned block_q: the reference blocks
        its q rows the same way, so a CPU engine constructed on a tile
        file stays exact."""
        prob = TestKernelBitEquivalence()
        q, k, v, ptab, slot, pos, _, _ = prob._problem()
        base = np.asarray(ragged_paged_attention(
            q, k, v, ptab, slot, pos, use_pallas=False))
        out = np.asarray(ragged_paged_attention(
            q, k, v, ptab, slot, pos, use_pallas=False, block_q=16))
        assert np.array_equal(base, out)

    def test_illegal_tiles_rejected_loudly(self):
        prob = TestKernelBitEquivalence()
        q, k, v, ptab, slot, pos, _, _ = prob._problem()
        with pytest.raises(ValueError, match="block_q"):
            ragged_paged_attention(q, k, v, ptab, slot, pos,
                                   use_pallas=True, interpret=True,
                                   block_q=6)   # not sublane-aligned
        with pytest.raises(ValueError, match="block_pages"):
            ragged_paged_attention(q, k, v, ptab, slot, pos,
                                   use_pallas=True, interpret=True,
                                   block_pages=-1)


# ---------------------------------------------------------------------------
# Token identity: ragged == bucketed, every mode, both pumps
# ---------------------------------------------------------------------------
def _submit_mixed(eng, max_new=8):
    eng.submit(Request("g0", [1, 5, 9, 3, 7], max_new_tokens=max_new))
    eng.submit(Request("s0", [2, 4, 6], max_new_tokens=max_new,
                       temperature=0.8, top_k=8, top_p=0.9, seed=123))
    eng.submit(Request("g1", [9, 9, 2], max_new_tokens=max_new,
                       logprobs=True))
    eng.submit(Request("s1", [7, 1], max_new_tokens=max_new,
                       temperature=1.1, seed=7, logprobs=True))


def _outputs(done):
    return {r.rid: (list(r.output), None if r.logprobs is None
                    else [round(v, 5) for v in r.logprobs])
            for r in done}


MODES = {
    "plain": {},
    "int8": {"cache_dtype": "int8"},
    "prefix": {"prefix_cache": True},
    "tier": {"prefix_cache": True, "host_tier_bytes": 1 << 20},
    "spec": {"spec_decode": 4},
    "chunked": {"spec_decode": 4, "chunked_prefill": True},
}
# the tier-1 budget carries one composition per distinct ragged code
# path (plain carry, quantized scatter, shared-page suffix prefill,
# spec verify-grid) under the sync pump plus the plain pipelined pump;
# the heavier compositions and remaining pump crosses run in the slow
# lane
_FAST = {("plain", False), ("plain", True), ("int8", False),
         ("prefix", False), ("spec", False)}
_PARAMS = [pytest.param(m, p, marks=()
                        if (m, p) in _FAST else pytest.mark.slow,
                        id=f"{m}-{'pipelined' if p else 'sync'}")
           for m in sorted(MODES) for p in (False, True)]


class TestTokenIdentity:
    """ragged=True == ragged=False, token for token and logprob for
    logprob, under the same pump."""

    @pytest.mark.parametrize("mode,pipelined", _PARAMS)
    def test_ragged_equals_bucketed(self, params, mode, pipelined):
        kw = MODES[mode]
        outs = []
        for ragged in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False,
                                ragged=ragged, **kw)
            _submit_mixed(eng)
            done = eng.run_pipelined() if pipelined else eng.run()
            assert len(done) == 4
            outs.append(_outputs(done))
        for rid, (toks, lps) in outs[0].items():
            r_toks, r_lps = outs[1][rid]
            # TOKEN identity is the contract, every mode
            assert toks == r_toks, f"mode {mode} rid {rid} diverged"
            if lps is None:
                assert r_lps is None
            elif mode == "int8":
                # int8 dequantizes inside the ragged attention kernel
                # but ahead of it in the bucketed one — same tokens,
                # logprobs drift at float rounding
                assert np.allclose(lps, r_lps, atol=1e-3), rid
            else:
                assert lps == r_lps, f"mode {mode} rid {rid} logprobs"

    @pytest.mark.parametrize("pipelined", [False, True],
                             ids=["sync", "pipelined"])
    def test_ragged_under_preemption(self, params, pipelined):
        """An oversubscribed pool forces preemption mid-run: the
        ragged engine must stall/preempt exactly like the bucketed one
        and emit the same tokens."""
        outs = []
        for ragged in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                                page_size=8, num_pages=6,
                                use_pallas=False, ragged=ragged)
            eng.submit(Request("s", [3, 7, 2, 9], max_new_tokens=20,
                               temperature=0.8, top_k=8, seed=123))
            eng.submit(Request("g", [1, 4, 6, 2], max_new_tokens=20))
            done = eng.run_pipelined(max_steps=500) if pipelined \
                else eng.run(max_steps=500)
            assert eng.preemptions > 0
            outs.append({r.rid: r.output for r in done})
        assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# Zero retrace across mix changes + pad-waste telemetry
# ---------------------------------------------------------------------------
class TestRaggedTelemetry:
    def test_mix_change_zero_retrace(self, params):
        """Acceptance: prefill-heavy wave, mixed wave, decode-only
        wave, chunk-tail wave — ONE `serving.unified_step` trace
        serves them all; a mix change never retraces."""
        from paddle_tpu.observability.compile_telemetry import REGISTRY
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=True)
        eng.submit(Request("warm", [1, 2, 3], max_new_tokens=2))
        eng.run()
        fns = REGISTRY.snapshot()
        fns = fns.get("functions", fns)
        before = fns["serving.unified_step"]["compiles"]
        assert before >= 1
        # wildly different mixes: long prefill + short, staggered
        # admissions (prefill rows next to decode rows), sampled +
        # greedy, lengths crossing page boundaries
        eng.submit(Request("a", list(range(1, 20)), max_new_tokens=6))
        eng.submit(Request("b", [5], max_new_tokens=9,
                           temperature=0.7, top_k=4, seed=3))
        eng.submit(Request("c", [8, 8, 8, 8, 8, 8, 8], max_new_tokens=4))
        eng.run()
        fns = REGISTRY.snapshot()
        fns = fns.get("functions", fns)
        assert fns["serving.unified_step"]["compiles"] == before, \
            "mix change retraced unified_step"

    def test_pad_counters(self, params):
        """ragged: zero pad tokens ever booked, ragged rows counted;
        bucketed: the same workload pads. Counters surface through
        EngineMetrics with the `_total` rendering."""
        books = {}
        for ragged in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False,
                                ragged=ragged)
            reg = MetricsRegistry()
            sched = RequestScheduler(eng, max_queue=8, metrics=reg)
            hs = [sched.submit([1 + i, 5, 9], rid=f"r{i}",
                               max_new_tokens=5) for i in range(3)]
            for h in hs:
                h.result(timeout=60)
            sched.shutdown(drain=True, timeout=30)
            snap = reg.snapshot()
            books[ragged] = (eng.pad_tokens, eng.ragged_tokens,
                             snap["pt_pad_tokens"]["value"],
                             snap["pt_ragged_tokens"]["value"],
                             reg.render_prometheus())
        pad, rag, m_pad, m_rag, text = books[True]
        assert pad == 0 and m_pad == 0
        assert rag > 0 and m_rag == rag
        assert "pt_ragged_tokens_total" in text
        assert "pt_pad_tokens_total 0" in text
        b_pad, b_rag, b_m_pad, _, _ = books[False]
        assert b_pad > 0 and b_m_pad == b_pad
        assert b_rag == 0


# ---------------------------------------------------------------------------
# PT_FAULTS crash-recovery drill: step_launch crash under ragged
# ---------------------------------------------------------------------------
class TestFaultDrill:
    N = 4

    def _drill(self, params, pipelined):
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=True)
        sched = RequestScheduler(eng, max_queue=16,
                                 metrics=MetricsRegistry(),
                                 pipeline=pipelined)
        sched.pause()
        hs = [sched.submit([1 + i, 5, 9, 3], rid=f"r{i}",
                           max_new_tokens=8) for i in range(self.N)]
        sched.resume()
        outs = {h.rid: h.result(timeout=90) for h in hs}
        st = sched.stats()
        sched.shutdown(drain=True, timeout=30)
        c = eng.pool.counts()
        assert c["free"] + c["cached"] + c["live"] == eng.num_pages - 1
        return outs, st

    @pytest.mark.parametrize("pipelined",
                             [False, pytest.param(True,
                                                  marks=pytest.mark.slow)],
                             ids=["sync", "pipelined"])
    def test_step_launch_crash_recovers_token_identical(
            self, params, pipelined, monkeypatch):
        monkeypatch.delenv("PT_FAULTS", raising=False)
        base, st = self._drill(params, pipelined)
        assert st["recovery"]["restarts"] == 0
        # a transient device-program crash on the 3rd launched wave:
        # warm restart + requeue, nobody fails, tokens identical
        monkeypatch.setenv("PT_FAULTS", "step_launch:raise@3")
        outs, st = self._drill(params, pipelined)
        assert outs == base
        assert st["recovery"]["restarts"] >= 1
        assert st["requests"]["failed"] == 0
        assert st["requests"]["completed"] == self.N


# ---------------------------------------------------------------------------
# Lean row-sparse lm_head epilogue (ISSUE 12)
# ---------------------------------------------------------------------------
class TestLeanEpilogue:
    """lean=True (the default) vs lean=False at equal config: tokens
    AND logprobs identical, the step program strictly cheaper, the
    skipped unembed rows booked in pt_logit_rows(_skipped)."""

    def _run(self, params, lean, kw, pipelined, spec_workload):
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=True,
                            lean=lean, **kw)
        if spec_workload:
            # spec modes draft off n-gram repeats; the lean engine
            # consumes device candidate probs in the rejection sampler
            # (a documented sampling-trajectory change, docs/serving.md
            # § Speculative row narrowing), so the lean-vs-full
            # identity contract is asserted on the greedy verify path
            eng.submit(Request("g0", [1, 5, 1, 5, 1, 5], max_new_tokens=8))
            eng.submit(Request("g1", [9, 9, 9, 2], max_new_tokens=8,
                               logprobs=True))
            eng.submit(Request("g2", [2, 4, 2, 4, 2], max_new_tokens=8,
                               logprobs=True))
        else:
            _submit_mixed(eng)
        done = eng.run_pipelined() if pipelined else eng.run()
        return eng, _outputs(done)

    @pytest.mark.parametrize("mode,pipelined", _PARAMS)
    def test_lean_equals_full(self, params, mode, pipelined):
        kw = MODES[mode]
        spec_workload = bool(kw.get("spec_decode"))
        outs = []
        for lean in (False, True):
            eng, out = self._run(params, lean, kw, pipelined,
                                 spec_workload)
            if lean:
                assert eng.logit_rows_skipped > 0
            else:
                assert eng.logit_rows_skipped == 0
            outs.append(out)
        for rid, (toks, lps) in outs[0].items():
            l_toks, l_lps = outs[1][rid]
            assert toks == l_toks, f"mode {mode} rid {rid} diverged"
            assert lps == l_lps, f"mode {mode} rid {rid} logprobs"

    def test_lean_under_preemption(self, params):
        outs = []
        for lean in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                                page_size=8, num_pages=6,
                                use_pallas=False, ragged=True, lean=lean)
            eng.submit(Request("s", [3, 7, 2, 9], max_new_tokens=20,
                               temperature=0.8, top_k=8, seed=123))
            eng.submit(Request("g", [1, 4, 6, 2], max_new_tokens=20))
            done = eng.run(max_steps=500)
            assert eng.preemptions > 0
            outs.append({r.rid: r.output for r in done})
        assert outs[0] == outs[1]

    def test_step_program_strictly_cheaper(self, params):
        """The whole point, asserted at the XLA cost-analysis layer:
        the lean `unified_step` issues FEWER flops AND touches fewer
        bytes than the full one on the same workload — the (T, vocab)
        unembed buffer is gone, not merely masked."""
        from paddle_tpu.observability import device_telemetry as _dt

        def step_cost(lean):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False,
                                ragged=True, lean=lean)
            zero = {"flops": 0.0, "bytes": 0.0}
            mark = _dt.COSTS.issued_totals()["per_fn"].get(
                "serving.unified_step", zero)
            _submit_mixed(eng)
            eng.run()
            now = _dt.COSTS.issued_totals()["per_fn"][
                "serving.unified_step"]
            return (now["flops"] - mark["flops"],
                    now["bytes"] - mark["bytes"])

        full, lean = step_cost(False), step_cost(True)
        assert 0 < lean[0] < full[0], (lean, full)
        assert 0 < lean[1] < full[1], (lean, full)

    def test_row_ledger_reaches_metrics(self, params):
        """pt_logit_rows / pt_logit_rows_skipped mirror the engine's
        counters through EngineMetrics and render with the counter
        `_total` suffix."""
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=True)
        assert eng.lean   # PT_SERVE_LEAN defaults ON
        reg = MetricsRegistry()
        sched = RequestScheduler(eng, max_queue=8, metrics=reg)
        hs = [sched.submit([1 + i, 5, 9], rid=f"r{i}",
                           max_new_tokens=5) for i in range(3)]
        for h in hs:
            h.result(timeout=60)
        sched.shutdown(drain=True, timeout=30)
        snap = reg.snapshot()
        assert eng.logit_rows > 0
        assert eng.logit_rows_skipped > 0
        assert snap["pt_logit_rows"]["value"] == eng.logit_rows
        assert snap["pt_logit_rows_skipped"]["value"] == \
            eng.logit_rows_skipped
        text = reg.render_prometheus()
        assert "pt_logit_rows_total" in text
        assert "pt_logit_rows_skipped_total" in text

    def test_need_rows_zero_retrace(self, params):
        """The need descriptor is a fixed-shape (max_seqs * G,) operand:
        waves with wildly different needed-row counts reuse ONE
        `serving.unified_step` trace."""
        from paddle_tpu.observability.compile_telemetry import REGISTRY
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=True,
                            lean=True)
        eng.submit(Request("warm", [1, 2, 3], max_new_tokens=2))
        eng.run()
        before = REGISTRY.snapshot()["serving.unified_step"]["compiles"]
        assert before >= 1
        # one long prefill (1 needed row), then a full decode batch
        # (max_seqs needed rows), then staggered admissions
        eng.submit(Request("a", list(range(1, 20)), max_new_tokens=6))
        eng.run()
        eng.submit(Request("b", [5], max_new_tokens=9))
        eng.submit(Request("c", [8, 8, 8], max_new_tokens=4))
        eng.run()
        after = REGISTRY.snapshot()["serving.unified_step"]["compiles"]
        assert after == before, "need_rows churn retraced unified_step"
