"""Engine crash recovery (ISSUE 9): deterministic fault injection
(serving/faults.py), warm restart with request requeue, poison-request
quarantine, and the crash-loop breaker — proven by replayable chaos
drills over real engines (and real HTTP where the acceptance criteria
ask for it), under BOTH the synchronous and pipelined pumps."""
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import Request, ServingEngine
from paddle_tpu.serving import (CrashLoopError, FaultPlan, HostTier,
                                InjectedFault, MetricsRegistry,
                                PoisonedRequestError, Replica,
                                RequestScheduler, Router, SchedulerError,
                                ServingClient, ServingHTTPError,
                                ServingServer, build_replicas)

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)
PAGE = 8


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def _engine(params, faults=None, **kw):
    kw.setdefault("max_seqs", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("use_pallas", False)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(params, CFG, faults=faults, **kw)


def _pool_conserved(eng, drained=False):
    """Conservation always; with `drained=True` additionally no page
    may still be LIVE — an incref leaked across a crash would satisfy
    conservation (the page counts as live) but never be reclaimable."""
    c = eng.pool.counts()
    ok = c["free"] + c["cached"] + c["live"] == eng.num_pages - 1
    if drained:
        ok = ok and c["live"] == 0
    return ok


# ---------------------------------------------------------------------------
# FaultPlan: the deterministic harness itself
# ---------------------------------------------------------------------------
class TestFaultPlan:
    def test_grammar_round_trip(self):
        plan = FaultPlan("seed=7;step_launch:raise@3;"
                         "tier_spill:delay@1x2:delay=0.0;"
                         "step_finish:raise@2x*:rid=bad,msg=boom")
        assert plan.seed == 7
        st = plan.stats()
        assert [r["rule"] for r in st["rules"]] == [
            "step_launch:raise@3x1", "tier_spill:delay@1x2",
            "step_finish:raise@2x*:rid=bad"]

    @pytest.mark.parametrize("spec", [
        "nope:raise@1",            # unknown point
        "step_launch:explode@1",   # unknown action
        "step_launch:raise",       # missing @first
        "step_launch@1",           # missing action
        "step_launch:raise@0",     # hits are 1-based
        "step_launch:raise@1:wat=1",  # unknown arg
    ])
    def test_bad_specs_fail_fast(self, spec):
        with pytest.raises(ValueError):
            FaultPlan(spec)

    def test_nth_hit_and_run_length(self):
        plan = FaultPlan("step_launch:raise@3x2")
        for hit in range(1, 7):
            if hit in (3, 4):
                with pytest.raises(InjectedFault) as ei:
                    plan.fire("step_launch")
                assert ei.value.point == "step_launch"
                assert ei.value.hit == hit
            else:
                plan.fire("step_launch")
        assert plan.hits["step_launch"] == 6
        assert len(plan.fired) == 2

    def test_rid_scoped_rule_counts_matching_hits_only(self):
        plan = FaultPlan("step_launch:raise@2x*:rid=bad")
        plan.fire("step_launch", rids=["bad"])       # match 1: below first
        plan.fire("step_launch", rids=["good"])      # no match
        with pytest.raises(InjectedFault):
            plan.fire("step_launch", rids=["good", "bad"])  # match 2
        with pytest.raises(InjectedFault):
            plan.fire("step_launch", rids=["bad"])          # match 3
        assert len(plan.fired) == 2
        assert plan.hits["step_launch"] == 4

    def test_corrupt_is_deterministic_and_seeded(self):
        a = np.arange(32, dtype=np.float32).reshape(4, 8)
        flips = []
        for _ in range(2):
            plan = FaultPlan("tier_spill:corrupt@1", seed=5)
            out = plan.fire("tier_spill", a.copy())
            assert (out != a).sum() == 1      # exactly one element hit
            flips.append(np.argwhere(out != a).tolist())
        assert flips[0] == flips[1]           # same seed -> same flip
        # untouched input: corrupt copies, never mutates in place
        ref = FaultPlan("tier_spill:corrupt@1", seed=5)
        src = a.copy()
        ref.fire("tier_spill", src)
        assert np.array_equal(src, a)

    def test_delay_and_infinite_count(self):
        plan = FaultPlan("router_dispatch:delay@1x*:delay=0.01")
        t0 = time.perf_counter()
        plan.fire("router_dispatch")
        plan.fire("router_dispatch")
        assert time.perf_counter() - t0 >= 0.02

    def test_from_env(self, monkeypatch):
        monkeypatch.delenv("PT_FAULTS", raising=False)
        assert FaultPlan.from_env() is None
        monkeypatch.setenv("PT_FAULTS", "step_launch:raise@1")
        plan = FaultPlan.from_env()
        assert plan is not None
        eng_plan = FaultPlan.from_env({"PT_FAULTS": "seed=3;"
                                       "tier_spill:raise@2"})
        assert eng_plan.seed == 3

    def test_engine_defaults_off(self, params):
        """faults disabled (no PT_FAULTS, no kwarg) must cost nothing:
        plan is None and the engine behaves exactly as seeded."""
        eng = _engine(params)
        assert eng.faults is None and eng.host_tier.faults is None
        eng.submit(Request("a", [1, 2, 3], max_new_tokens=4))
        done = eng.run()
        assert len(done[0].output) == 4


# ---------------------------------------------------------------------------
# Acceptance e2e: chaos drill over real HTTP, both pumps
# ---------------------------------------------------------------------------
class TestChaosDrillHTTP:
    """N concurrent HTTP requests, an injected device failure
    mid-decode: ZERO requests fail (transient fault), every output is
    token-identical to an undisturbed run, pt_engine_restarts_total
    >= 1 on /metrics, and the requeue ledger balances — under both the
    synchronous and the pipelined pump."""

    N = 5

    def _drill(self, params, faults, pipeline):
        eng = _engine(params, faults=faults)
        sched = RequestScheduler(eng, max_queue=32,
                                 metrics=MetricsRegistry(),
                                 pipeline=pipeline)
        srv = ServingServer(sched, port=0).start()
        cl = ServingClient(port=srv.port)
        sched.pause()
        results = {}

        def call(i):
            kw = {"max_tokens": 10}
            if i % 2:
                kw.update(temperature=0.8, top_k=8, seed=100 + i)
            results[i] = cl.complete([1 + i, 5, 9, 3], **kw)
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(self.N)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                sched.stats()["queued"] < self.N:
            time.sleep(0.01)
        sched.resume()
        for t in threads:
            t.join(timeout=90)
        assert not any(t.is_alive() for t in threads)
        text = cl.metrics_text()
        health = cl.healthz()
        srv.stop(drain=True, timeout=30)
        assert _pool_conserved(eng)
        return results, text, health

    @pytest.mark.parametrize("pipeline", [False, True])
    def test_transient_fault_is_invisible(self, params, pipeline):
        base, _, _ = self._drill(params, None, pipeline)
        assert all(r["state"] == "done" for r in base.values())
        chaos, text, health = self._drill(
            params, FaultPlan("step_launch:raise@4"), pipeline)
        # zero casualties, token-identical to the undisturbed run
        for i in range(self.N):
            assert chaos[i]["state"] == "done", (pipeline, i, chaos[i])
            assert chaos[i]["tokens"] == base[i]["tokens"], (pipeline, i)
        # the restart really happened and is on /metrics
        restarts = [ln for ln in text.splitlines()
                    if ln.startswith("pt_engine_restarts_total ")][0]
        assert float(restarts.split()[-1]) >= 1
        requeued = [ln for ln in text.splitlines()
                    if ln.startswith("pt_requests_requeued_total ")][0]
        assert float(requeued.split()[-1]) >= 1
        assert "pt_engine_restart_seconds_bucket" in text
        # requeue ledger balances: conservation with requeues counted
        # once, surfaced on /healthz
        led = health["requests"]
        assert led["requeued"] >= 1
        assert led["submitted"] == (
            led["completed"] + led["failed"] + led["cancelled"]
            + led["expired"] + health["queued"] + health["inflight"])
        assert health["recovery"]["restarts"] >= 1
        assert health["recovery"]["breaker_open"] is False


# ---------------------------------------------------------------------------
# Poison quarantine: exactly the poisoned request fails
# ---------------------------------------------------------------------------
class TestPoisonQuarantine:
    def _run(self, params, faults, pipeline, poison_after=2):
        eng = _engine(params, faults=faults)
        sched = RequestScheduler(eng, max_queue=16,
                                 metrics=MetricsRegistry(),
                                 pipeline=pipeline,
                                 poison_after=poison_after,
                                 max_restarts=50)
        sched.pause()
        hs = [sched.submit([1 + i, 5, 9, 3], rid=f"r{i}",
                           max_new_tokens=8) for i in range(3)]
        bad = sched.submit([9, 9, 9, 9], rid="bad", max_new_tokens=8) \
            if faults is not None else None
        sched.resume()
        outs = {h.rid: h.result(timeout=90) for h in hs}
        err = None
        if bad is not None:
            with pytest.raises(PoisonedRequestError) as ei:
                bad.result(timeout=90)
            err = ei.value
        st = sched.stats()
        snap = sched.metrics_snapshot()
        sched.shutdown(drain=True, timeout=30)
        assert _pool_conserved(eng)
        return outs, err, st, snap

    @pytest.mark.parametrize("pipeline", [False, True])
    def test_poison_fails_alone_innocents_complete(self, params,
                                                   pipeline):
        base, _, _, _ = self._run(params, None, pipeline)
        outs, err, st, snap = self._run(
            params, FaultPlan("step_launch:raise@1x*:rid=bad"), pipeline)
        # exactly the poisoned request failed, with a client-readable
        # `poisoned` error; every innocent is token-identical
        assert outs == base
        assert "poisoned" in str(err)
        assert st["requests"]["failed"] == 1
        assert st["recovery"]["quarantined"] == 1
        assert snap["pt_poison_quarantined"]["value"] == 1
        assert snap["pt_engine_restarts"]["value"] >= 2

    def test_quarantine_leaves_flight_trail(self, params):
        from paddle_tpu.observability import flight_recorder as _flight
        self._run(params, FaultPlan("step_launch:raise@1x*:rid=bad"),
                  False)
        evs = _flight.snapshot()["events"]
        q = [e for e in evs if e.get("kind") == "poison.quarantine"]
        assert q and q[-1]["rid"] == "bad" and q[-1].get("trace_id")
        r = [e for e in evs if e.get("kind") == "engine.restart"]
        assert r and all("trace_ids" in e for e in r)
        inj = [e for e in evs if e.get("kind") == "fault.injected"]
        assert inj and inj[-1]["point"] == "step_launch"

    def test_mid_stream_crash_fails_not_requeues(self, params):
        """A request whose consumer has SEEN bytes must fail on crash
        (never silently replay), and it publishes nothing further."""
        eng = _engine(params, max_seq_len=512)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry())
        h = sched.submit([1, 2, 3], max_new_tokens=400)
        got = []
        it = h.stream(timeout=30)
        got.extend(next(it))
        plan = eng.faults = FaultPlan()
        plan.add("step_launch", "raise", count=None,
                 exc=RuntimeError("mid-stream crash"))
        with pytest.raises(SchedulerError):
            for chunk in it:
                got.extend(chunk)
        assert h.state == "failed"
        assert h._streamed and h._requeues == 0
        # no bytes published after the failure
        assert len(got) == h._emitted
        sched.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# Crash-loop breaker: intra-replica exhaustion -> cross-replica failover
# ---------------------------------------------------------------------------
class TestCrashLoopBreaker:
    def test_breaker_flips_readyz_and_refuses_with_retry_after(
            self, params):
        rep = Replica("r0", _engine(params), max_restarts=2,
                      restart_window_s=60.0, poison_after=99)
        srv = ServingServer(rep.scheduler, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            assert cl.readyz()["ready"] is True
            rep.kill()
            h = rep.submit([1, 2, 3], max_new_tokens=8)
            with pytest.raises(SchedulerError):
                h.result(timeout=60)
            # breaker open: /readyz 503 with the reason, admission 503
            # with Retry-After
            with pytest.raises(ServingHTTPError) as ei:
                cl.readyz()
            assert ei.value.status == 503
            assert ei.value.body["detail"] == "crash_loop"
            with pytest.raises(ServingHTTPError) as ei:
                cl.complete([1, 2, 3], max_tokens=2)
            assert ei.value.status == 503
            assert ei.value.retry_after_s is not None
            with pytest.raises(CrashLoopError):
                rep.submit([1, 2, 3], max_new_tokens=2)
            # revive closes the breaker and the replica serves again
            rep.revive()
            assert cl.readyz()["ready"] is True
            out = cl.complete([1, 2, 3], max_tokens=4)
            assert out["state"] == "done" and len(out["tokens"]) == 4
        finally:
            srv.stop(drain=False, timeout=30)

    def test_client_retries_breaker_503_honoring_retry_after(
            self, params):
        """Satellite: a crash-loop-breaker replica behind a
        single-replica deployment is retried by the client (bounded,
        Retry-After honored) instead of surfaced."""
        rep = Replica("r0", _engine(params), max_restarts=1,
                      restart_window_s=60.0, poison_after=99,
                      breaker_retry_after_s=1.0)
        srv = ServingServer(rep.scheduler, port=0).start()
        try:
            rep.kill()
            h = rep.submit([4, 4, 4], max_new_tokens=4)
            with pytest.raises(SchedulerError):
                h.result(timeout=60)
            assert not rep.ready()
            reviver = threading.Timer(0.3, rep.revive)
            reviver.start()
            try:
                cl = ServingClient(port=srv.port, timeout=30, retries=8,
                                   retry_cap_s=0.4)
                out = cl.complete([1, 2, 3], max_tokens=4)
                assert out["state"] == "done"
            finally:
                reviver.cancel()
            # a bare 503 (shutdown, no Retry-After) is NOT retried
            rep.shutdown(drain=True, timeout=30)
            with pytest.raises(ServingHTTPError) as ei:
                ServingClient(port=srv.port, retries=3).complete(
                    [1, 2, 3], max_tokens=2)
            assert ei.value.status == 503
            assert ei.value.retry_after_s is None
        finally:
            srv.stop(drain=False, timeout=30)

    def test_breaker_fails_over_to_healthy_replica(self, params):
        """Acceptance crash-loop drill: a persistent fault burns
        through requeues, trips the breaker, the router marks the
        replica unhealthy and fails queued work over token-identically;
        revive + probe recovery restores rotation."""
        def factory(i):
            return _engine(params, max_seqs=2)
        reps = build_replicas(factory, 2, max_queue=16,
                              max_restarts=2, restart_window_s=60.0,
                              poison_after=99)
        router = Router(reps, unhealthy_after=2, probe_after_s=30.0)
        try:
            prompt = [3, 1, 4, 1, 5]
            ref = None
            probe = _engine(params)
            probe.submit(Request("ref", prompt, max_new_tokens=6))
            ref = probe.run()[0].output
            target = router.affinity_target(prompt)
            rep = router.replica(target)
            rep.pause()
            held = [router.submit(prompt, max_new_tokens=6)
                    for _ in range(2)]
            rep.kill()
            rep.resume()
            outs = [r.result(timeout=90) for r in held]
            assert outs == [ref, ref]
            assert all(r.state == "done" and r.failovers >= 1
                       for r in held)
            assert all(r.replica_id != target for r in held)
            # the dead replica: breaker open, router marked unhealthy
            assert not rep.ready()
            assert rep.scheduler.readiness()[1] == "crash_loop"
            st = router.stats()["replicas"][target]
            assert st["health"] == "open" and st["ready"] is False
            # revive + probe recovery restores rotation
            rep.revive()
            assert rep.ready()
            with router._lock:
                router._replicas[target].opened_at = \
                    time.monotonic() - 31.0
            rr = router.submit(prompt, max_new_tokens=6)
            assert rr.replica_id == target
            assert rr.result(timeout=60) == ref
            assert router.stats()["replicas"][target]["health"] == "ok"
        finally:
            router.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# Fault points beyond the decode dispatch
# ---------------------------------------------------------------------------
class TestOtherFaultPoints:
    @pytest.mark.parametrize("pipeline", [False, True])
    def test_step_finish_fault_with_pending_ticket(self, params,
                                                   pipeline):
        """A crash at the async result read — under the pipelined pump
        that is a pending step_finish ticket at crash time — recovers
        token-identically."""
        outs = []
        for spec in (None, "step_finish:raise@3"):
            eng = _engine(params,
                          faults=None if spec is None
                          else FaultPlan(spec))
            sched = RequestScheduler(eng, max_queue=8,
                                     metrics=MetricsRegistry(),
                                     pipeline=pipeline)
            sched.pause()
            hs = [sched.submit([2 + i, 7, 1], max_new_tokens=8,
                               **({"temperature": 0.7, "seed": 42}
                                  if i == 1 else {}))
                  for i in range(3)]
            sched.resume()
            outs.append([h.result(timeout=90) for h in hs])
            if spec is not None:
                assert sched.stats()["requests"]["requeued"] >= 1
            sched.shutdown(drain=True, timeout=30)
            assert _pool_conserved(eng)
        assert outs[0] == outs[1]

    def test_suffix_prefill_fault_recovers_conserving_pool(self, params):
        """A crash inside the prefix-cache suffix prefill (mid-
        admission: pages mapped, slot not yet attached) must release
        everything and recover."""
        # bucketed machinery under test: the ragged engine admits via
        # the chunked feed and never enters the suffix-prefill entry
        # point (its fault drill lives in test_ragged_step.py)
        eng = _engine(params, ragged=False,
                      faults=FaultPlan("suffix_prefill:raise@2"))
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry())
        h = [1, 2, 3, 4, 5, 6, 7, 8, 9]   # > one full page
        a = sched.submit(h + [1], max_new_tokens=4)
        a.result(timeout=60)
        # same header: the second admission goes suffix-prefill; hit 2
        # of the point crashes it mid-admission
        b = sched.submit(h + [2], max_new_tokens=4)
        c = sched.submit(h + [3], max_new_tokens=4)
        rb, rc = b.result(timeout=90), c.result(timeout=90)
        assert len(rb) == 4 and len(rc) == 4
        assert sched.stats()["requests"]["requeued"] >= 1
        sched.shutdown(drain=True, timeout=30)
        assert _pool_conserved(eng, drained=True)

    def test_tier_restore_fault_recovers(self, params):
        eng = _engine(params, host_tier_bytes=1 << 20,
                      faults=FaultPlan("tier_restore:raise@1"))
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry())
        h = [5, 6, 7, 8, 1, 2, 3, 4, 9]
        sched.submit(h + [1], max_new_tokens=4).result(timeout=60)
        sched.drain(timeout=10)
        # force the header's pages out of the device cache into the tier
        eng.host_tier.flush(timeout=10)
        evict = [sched.submit([11 + i, 13, 17, 19] * 4, max_new_tokens=4)
                 for i in range(4)]
        [e.result(timeout=60) for e in evict]
        sched.drain(timeout=10)
        eng.host_tier.flush(timeout=10)
        # returning conversation: tier restore fires the fault once,
        # recovery retries and completes
        out = sched.submit(h + [1], max_new_tokens=4).result(timeout=90)
        assert len(out) == 4
        sched.shutdown(drain=True, timeout=30)
        assert _pool_conserved(eng, drained=True)

    def test_kill_is_a_fault_plan_rule(self, params):
        rep = Replica("rX", _engine(params))
        assert rep.engine.faults is None
        rep.kill()
        st = rep.engine.faults.stats()
        assert any(r["label"] == "kill:rX" for r in st["rules"])
        rep.revive()
        assert not rep.engine.faults.stats()["rules"]
        out = rep.submit([1, 2, 3], max_new_tokens=3).result(timeout=60)
        assert len(out) == 3
        rep.shutdown(drain=True, timeout=30)


# ---------------------------------------------------------------------------
# Satellite: kvtier copy-thread hardening
# ---------------------------------------------------------------------------
class TestTierCopyHardening:
    def test_one_bad_copy_costs_one_page(self):
        """A spill copy that raises drops THAT page, counts the error,
        records the evidence, and the worker keeps landing later
        spills."""
        from paddle_tpu.observability import flight_recorder as _flight
        tier = HostTier(page_size=4, tier_bytes=1 << 20)
        tier.faults = FaultPlan("tier_spill:raise@1")
        k = np.ones((2, 2, 4, 8), np.float32)
        tier.spill_async(b"p0", (1, 2, 3, 4), 0, k, k)   # injected fail
        tier.spill_async(b"p1", (5, 6, 7, 8), 0, k, k)   # must land
        assert tier.flush(timeout=10)
        st = tier.stats()
        assert st["copy_errors"] == 1
        assert st["spills"] == 1 and st["spilled_pages"] == 1
        assert tier._worker.is_alive()
        evs = _flight.snapshot()["events"]
        assert any(e.get("kind") == "kvtier.error" for e in evs)
        # exactly the SECOND page landed
        assert len(tier._entries) == 1
        (entry,) = tier._entries.values()
        assert entry["block"] == (5, 6, 7, 8)

    def test_copy_error_counter_on_metrics(self, params):
        """pt_prefix_tier_copy_errors_total mirrors the tier's rollup
        through the same single-writer on_step delta path as the other
        tier counters."""
        eng = _engine(params, host_tier_bytes=1 << 20)
        eng.host_tier.faults = FaultPlan("tier_spill:raise@1x*")
        reg = MetricsRegistry()
        from paddle_tpu.serving.metrics import EngineMetrics
        eng.metrics = EngineMetrics(reg)
        k = np.ones((2, 2, PAGE, 8), np.float32)
        eng.host_tier.spill_async(b"p", (1,) * PAGE, 0, k, k)
        assert eng.host_tier.flush(timeout=10)
        assert eng.host_tier.copy_errors == 1
        # a device step mirrors the tier rollups onto the registry
        eng.submit(Request("z", [2, 4, 6], max_new_tokens=2))
        eng.run()
        text = reg.render_prometheus()
        line = [ln for ln in text.splitlines()
                if ln.startswith("pt_prefix_tier_copy_errors_total ")]
        assert line and float(line[0].split()[-1]) == 1


# ---------------------------------------------------------------------------
# Satellite: ledger + ptdump
# ---------------------------------------------------------------------------
def test_ledger_requeued_monotonic_and_conserved(params):
    eng = _engine(params, faults=FaultPlan("step_launch:raise@2"))
    sched = RequestScheduler(eng, max_queue=8, metrics=MetricsRegistry())
    hs = [sched.submit([1 + i, 2], max_new_tokens=5) for i in range(3)]
    [h.result(timeout=60) for h in hs]
    st = sched.stats()
    led = st["requests"]
    assert led["requeued"] >= 1
    assert led["submitted"] == (
        led["completed"] + led["failed"] + led["cancelled"]
        + led["expired"] + st["queued"] + st["inflight"])
    # requeues counted once each: never more than restarts * inflight
    assert led["requeued"] <= st["recovery"]["restarts"] * 3
    sched.shutdown(drain=True, timeout=30)


def test_ptdump_rolls_up_restarts(tmp_path, capsys):
    import importlib.util
    import json
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ptdump", os.path.join(root, "tools", "ptdump.py"))
    ptdump = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ptdump)
    doc = {"pid": 1, "dumped_at": 0.0, "reason": "test", "capacity": 16,
           "dropped": 0, "events": [
               {"kind": "fault.injected", "ts": 0.5,
                "point": "step_launch", "hit": 4, "action": "raise"},
               {"kind": "engine.restart", "ts": 1.0, "requeued": 3,
                "failed": 0, "quarantined": 0, "broken": False,
                "duration_s": 0.002},
               {"kind": "engine.restart", "ts": 2.0, "requeued": 0,
                "failed": 2, "quarantined": 1, "broken": True,
                "duration_s": 0.001,
                "error": "ReplicaKilledError('dead')"}]}
    path = tmp_path / "dump.json"
    path.write_text(json.dumps(doc))
    assert ptdump.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "engine restarts: 2" in out
    assert "3 requeued, 2 failed, 1 quarantined" in out
    assert "1 injected faults" in out
    assert "crash-loop breaker OPEN" in out
