"""incubate.operators.ResNetUnit (reference: python/paddle/incubate/
operators/resnet_unit.py — the cudnnv8 fused block; here XLA fuses the
same conv+BN(+add)+act composition)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.incubate.operators import ResNetUnit


class TestResNetUnit:
    def test_shortcut_branch_matches_unfused(self):
        pt.seed(0)
        u = ResNetUnit(8, 16, 3, stride=2, data_format="NHWC",
                       has_shortcut=True, num_channels_z=8, stride_z=2)
        x = pt.randn([2, 16, 16, 8])
        out = u(x, x)
        assert out.shape == [2, 8, 8, 16]
        manual = pt.nn.functional.relu(
            u.bn_x(u.conv_x(x)) + u.bn_z(u.conv_z(x)))
        assert np.allclose(out.numpy(), manual.numpy(), atol=1e-5)

    def test_fuse_add_branch(self):
        pt.seed(1)
        u = ResNetUnit(8, 8, 3, fuse_add=True, data_format="NHWC")
        x, z = pt.randn([2, 12, 12, 8]), pt.randn([2, 12, 12, 8])
        out = u(x, z)
        manual = pt.nn.functional.relu(u.bn_x(u.conv_x(x)) + z)
        assert np.allclose(out.numpy(), manual.numpy(), atol=1e-5)

    def test_plain_branch_nchw_identity_act(self):
        pt.seed(2)
        u = ResNetUnit(4, 8, 3, data_format="NCHW", act="identity")
        x = pt.randn([2, 4, 10, 10])
        out = u(x)
        manual = u.bn_x(u.conv_x(x))
        assert np.allclose(out.numpy(), manual.numpy(), atol=1e-5)

    def test_train_eval_statistics(self):
        u = ResNetUnit(4, 8, 3, data_format="NHWC")
        x = pt.randn([4, 8, 8, 4]) * 3.0 + 1.0
        u.train()
        u(x)
        mean_after = u.bn_x._mean.numpy().copy()
        assert np.abs(mean_after).sum() > 0      # running stats updated
        u.eval()
        before = u.bn_x._mean.numpy().copy()
        u(x)
        assert np.allclose(u.bn_x._mean.numpy(), before)  # frozen in eval

    def test_gradients_flow(self):
        u = ResNetUnit(4, 8, 3, data_format="NHWC", has_shortcut=True,
                       num_channels_z=4)
        xn = np.random.RandomState(0).randn(2, 8, 8, 4).astype(np.float32)
        x = pt.to_tensor(xn, stop_gradient=False)
        u(x, x).sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert u.conv_x.weight.grad is not None
        assert u.conv_z.weight.grad is not None

    def test_guards(self):
        with pytest.raises(ValueError, match="conv_format"):
            ResNetUnit(4, 8, 3, data_format="NHCW")
        with pytest.raises(ValueError, match="act"):
            ResNetUnit(4, 8, 3, act="gelu")
        u = ResNetUnit(4, 8, 3, has_shortcut=True, num_channels_z=4)
        with pytest.raises(ValueError, match="requires z"):
            u(pt.randn([1, 8, 8, 4]))

    def test_is_test_gives_inference_behavior(self):
        u = ResNetUnit(4, 8, 3, data_format="NHWC", is_test=True)
        assert not u.training
        x = pt.randn([2, 8, 8, 4]) * 2.0
        before = u.bn_x._mean.numpy().copy()
        u(x)
        assert np.allclose(u.bn_x._mean.numpy(), before)

    def test_use_global_stats_false_forces_batch_stats_in_eval(self):
        """Reference semantics (functional/norm.py trainable_statistics):
        an explicit False means mini-batch statistics ALWAYS — eval
        included — while None switches to moving statistics in eval.
        The two must therefore DIVERGE after train()/eval()."""
        pt.seed(5)
        a = pt.nn.BatchNorm2D(4, use_global_stats=False,
                              data_format="NHWC")
        b = pt.nn.BatchNorm2D(4, use_global_stats=None,
                              data_format="NHWC")
        x = pt.randn([2, 6, 6, 4]) * 3.0 + 1.0
        for m in (a, b):
            m.train(); m(x); m.eval()
        oa, ob = a(x).numpy(), b(x).numpy()
        # False in eval: batch statistics -> output is ~zero-mean
        assert np.abs(oa.mean()) < 1e-5
        # None in eval: moving statistics, which after one momentum=0.9
        # update still sit near init (mean 0 / var 1) -> output keeps
        # most of x's offset and differs from the batch-normalized a
        assert np.abs(ob.mean()) > 1e-3
        assert not np.allclose(oa, ob, atol=1e-3)
