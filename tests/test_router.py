"""Scale-out serving tier (serving/router.py + serving/replica.py):
prefix-affinity dispatch over a replica pool, least-loaded spill under
backpressure, circuit-breaker health with half-open probes, failover of
queued-but-unstarted requests on replica death (token-identical to an
undisturbed run), graceful per-replica drain, and aggregated /metrics
with replica labels — all end-to-end in-process on CPU over real
engines, and over real HTTP where the acceptance criteria ask for it.
"""
import threading
import time

import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import Request, ServingEngine
from paddle_tpu.serving import (BackpressureError, ReplicaKilledError,
                                Router, ServingClient, ServingHTTPError,
                                ServingServer, build_replicas,
                                prefix_key)

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)
PAGE = 8


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def make_factory(params, max_seqs=2, max_seq_len=64, **kw):
    def factory(_i=0):
        return ServingEngine(params, CFG, max_seqs=max_seqs,
                             max_seq_len=max_seq_len, page_size=PAGE,
                             use_pallas=False, prefix_cache=True, **kw)
    return factory


def make_router(params, n=2, max_queue=16, **router_kw):
    reps = build_replicas(make_factory(params), n, max_queue=max_queue)
    return Router(reps, **router_kw)


def greedy_reference(params, prompt, n_new):
    ids = list(prompt)
    out = []
    for _ in range(n_new):
        logits = M.forward(params, jnp.asarray([ids]), CFG, mesh=None,
                           remat=False)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        ids.append(nxt)
    return out


def header(seed, blocks=2):
    """A deterministic shared system-prompt header of full pages."""
    return [(seed * 31 + i) % 60 + 1 for i in range(blocks * PAGE)]


class TestPrefixKey:
    def test_same_header_same_key_any_tail(self):
        h = header(1)
        k1, n1 = prefix_key(h + [7, 8], PAGE)
        k2, n2 = prefix_key(h + [9], PAGE)
        assert k1 == k2 and n1 == n2 == 2

    def test_matches_prefix_cache_cap(self):
        # exactly 2 blocks: capped one token short, like
        # PrefixCache.match — only 1 full block participates
        h = header(1)          # 16 tokens
        _, n = prefix_key(h, PAGE)
        assert n == (len(h) - 1) // PAGE == 1
        _, n_plus = prefix_key(h + [5], PAGE)
        assert n_plus == 2

    def test_short_prompts_colocate_by_raw_tokens(self):
        k1, n1 = prefix_key([1, 2, 3], PAGE)
        k2, _ = prefix_key([1, 2, 3], PAGE)
        k3, _ = prefix_key([1, 2, 4], PAGE)
        assert n1 == 0 and k1 == k2 and k1 != k3

    def test_different_headers_different_keys(self):
        ks = {prefix_key(header(s) + [1], PAGE)[0] for s in range(8)}
        assert len(ks) == 8


class TestAffinity:
    def test_shared_prefix_sticks_to_one_replica(self, params):
        router = make_router(params)
        try:
            h = header(3)
            target = router.affinity_target(h + [40])
            rids = []
            for t in range(4):
                rr = router.submit(h + [40 + t], max_new_tokens=3)
                rr.result(timeout=60)
                rids.append(rr.replica_id)
            assert rids == [target] * 4
            snap = router.registry.snapshot()
            assert snap["pt_router_affinity_hits"]["value"] == 4
            assert snap["pt_router_dispatches"]["value"] == 4
            # the affinity replica's prefix cache engaged: first
            # request missed, the rest hit the shared header
            pc = router.replica(target).engine.prefix_cache
            assert pc.hits == 3 and pc.lookups == 4
        finally:
            router.shutdown(drain=True, timeout=30)

    def test_affinity_beats_round_robin_hit_rate(self, params):
        """4 prompt groups x 4 requests: affinity routing misses once
        per group (the whole group lands on one replica); round-robin
        spreads each group over both replicas, so every group misses
        once PER REPLICA — measurably lower pt_prefix_hit_rate."""
        def run(policy):
            router = make_router(params, policy=policy)
            try:
                for g in range(4):
                    h = header(10 + g)
                    for t in range(4):
                        router.submit(h + [30 + t],
                                      max_new_tokens=3).result(timeout=60)
                hits = lookups = 0
                for rid in router.replica_ids:
                    pc = router.replica(rid).engine.prefix_cache
                    hits += pc.hits
                    lookups += pc.lookups
                return hits / lookups
            finally:
                router.shutdown(drain=True, timeout=30)
        affinity_rate = run("affinity")
        rr_rate = run("round_robin")
        assert affinity_rate == pytest.approx(12 / 16)
        assert rr_rate == pytest.approx(8 / 16)
        assert affinity_rate > rr_rate

    def test_outputs_token_identical_to_reference(self, params):
        router = make_router(params)
        try:
            h = header(5)
            for t in (1, 2):
                out = router.submit(h + [t],
                                    max_new_tokens=4).result(timeout=60)
                assert out == greedy_reference(params, h + [t], 4)
        finally:
            router.shutdown(drain=True, timeout=30)


class TestSpill:
    def test_backpressured_target_spills_to_least_loaded(self, params):
        router = make_router(params, max_queue=2)
        try:
            h = header(7)
            target = router.affinity_target(h + [1])
            other = [r for r in router.replica_ids if r != target][0]
            # freeze the affinity target's pump and fill its queue
            router.replica(target).pause()
            held = [router.submit(h + [1 + t], max_new_tokens=3)
                    for t in range(2)]
            assert all(r.replica_id == target for r in held)
            # target full -> the next request spills to the other one
            spilled = router.submit(h + [9], max_new_tokens=3)
            assert spilled.replica_id == other
            assert spilled.result(timeout=60) == greedy_reference(
                params, h + [9], 3)
            snap = router.registry.snapshot()
            assert snap["pt_router_spills"]["value"] >= 1
            router.replica(target).resume()
            for r in held:
                r.result(timeout=60)
        finally:
            router.shutdown(drain=True, timeout=30)

    def test_all_full_raises_backpressure(self, params):
        router = make_router(params, max_queue=1)
        try:
            router.pause()
            h = header(8)
            for rid in router.replica_ids:
                # fill each replica's queue (router walks the plan)
                router.submit(header(8) + [rid.__hash__() % 5],
                              max_new_tokens=2)
            with pytest.raises(BackpressureError):
                router.submit(h + [50], max_new_tokens=2)
            assert router.registry.snapshot()[
                "pt_router_rejects"]["value"] >= 1
        finally:
            router.resume()
            router.shutdown(drain=True, timeout=30)


class TestFailover:
    def test_replica_death_fails_over_queued_requests(self, params):
        router = make_router(params, max_queue=16, unhealthy_after=2)
        try:
            h = header(11)
            target = router.affinity_target(h + [1])
            rep = router.replica(target)
            # park requests in the target's queue, then kill it
            rep.pause()
            held = [router.submit(h + [1 + t], max_new_tokens=3)
                    for t in range(3)]
            rep.kill()
            rep.resume()
            outs = [r.result(timeout=60) for r in held]
            # token-identical to an undisturbed run
            for t, out in enumerate(outs):
                assert out == greedy_reference(params, h + [1 + t], 3)
            assert all(r.state == "done" for r in held)
            assert all(r.failovers >= 1 for r in held)
            assert all(r.replica_id != target for r in held)
            snap = router.registry.snapshot()
            assert snap["pt_router_failovers"]["value"] >= 3
            # consecutive failures opened the breaker
            st = router.stats()["replicas"][target]
            assert st["health"] == "open"
            assert snap["pt_router_unhealthy_transitions"]["value"] == 1
        finally:
            router.shutdown(drain=True, timeout=30)

    def test_unhealthy_replica_skipped_then_probe_recovers(self, params):
        router = make_router(params, unhealthy_after=1,
                             probe_after_s=30.0)
        try:
            h = header(12)
            target = router.affinity_target(h + [1])
            rep = router.replica(target)
            rep.kill()
            rr = router.submit(h + [1], max_new_tokens=2)
            assert rr.result(timeout=60) == greedy_reference(
                params, h + [1], 2)
            assert rr.failovers == 1
            assert router.stats()["replicas"][target]["health"] == "open"
            # while open (cooldown not elapsed): dispatch avoids the
            # corpse entirely
            rr2 = router.submit(h + [2], max_new_tokens=2)
            assert rr2.replica_id != target
            rr2.result(timeout=60)
            # replica restarts; rewind the breaker clock (determinism
            # instead of sleeping out a real cooldown) -> ONE probe
            # goes in, succeeds, closes the breaker
            rep.revive()
            with router._lock:
                router._replicas[target].opened_at = \
                    time.monotonic() - 31.0
            rr3 = router.submit(h + [3], max_new_tokens=2)
            assert rr3.replica_id == target
            assert rr3.result(timeout=60) == greedy_reference(
                params, h + [3], 2)
            assert router.stats()["replicas"][target]["health"] == "ok"
            assert router.registry.snapshot()[
                "pt_router_probes"]["value"] >= 1
        finally:
            router.shutdown(drain=True, timeout=30)

    def test_streams_fail_over_before_first_byte_only(self, params):
        router = make_router(params, max_queue=16)
        try:
            h = header(13)
            target = router.affinity_target(h + [1])
            rep = router.replica(target)
            rep.pause()
            rr = router.submit(h + [1], max_new_tokens=3)
            rep.kill()
            rep.resume()
            toks = [t for chunk in rr.stream(timeout=60) for t in chunk]
            assert toks == greedy_reference(params, h + [1], 3)
            assert rr.failovers == 1
        finally:
            router.shutdown(drain=True, timeout=30)

    def test_no_replica_left_raises_original_error(self, params):
        router = make_router(params)
        try:
            for rid in router.replica_ids:
                router.replica(rid).pause()
            held = router.submit(header(14) + [1], max_new_tokens=2)
            for rid in router.replica_ids:
                router.replica(rid).kill()
                router.replica(rid).resume()
            with pytest.raises(Exception) as ei:
                held.result(timeout=60)
            assert "killed" in str(ei.value) or "failed" in str(ei.value)
        finally:
            router.shutdown(drain=False, timeout=30)


class TestDrain:
    def test_graceful_drain_finishes_running_then_removes(self, params):
        router = make_router(params)
        try:
            h = header(15)
            target = router.affinity_target(h + [1])
            rr = router.submit(h + [1], max_new_tokens=20)
            # rolling restart: drain flips readiness off, lets the
            # running request finish, then drops the replica
            assert router.drain_replica(target, timeout=60)
            assert rr.state == "done"
            assert rr.result(timeout=5) == greedy_reference(
                params, h + [1], 20)
            assert target not in router.replica_ids
            # the drained replica's keys re-home deterministically
            rr2 = router.submit(h + [2], max_new_tokens=2)
            assert rr2.replica_id != target
            rr2.result(timeout=60)
            ready, detail = router.readiness()
            assert ready and target not in detail
        finally:
            router.shutdown(drain=True, timeout=30)

    def test_drain_last_replica_closes_router(self, params):
        router = make_router(params, n=1)
        assert router.drain_replica(router.replica_ids[0], timeout=60)
        ready, _ = router.readiness()
        assert not ready
        with pytest.raises(Exception):
            router.submit([1, 2, 3], max_new_tokens=2)


class TestRouterHTTP:
    """The acceptance e2e: router + 2 in-process replicas behind the
    real HTTP server, shared-system-prompt workload, replica killed
    mid-run -> queued requests fail over and complete token-identical,
    /metrics aggregates with replica labels and counts the failover."""

    @pytest.fixture()
    def served(self, params):
        router = make_router(params, max_queue=16, unhealthy_after=2)
        srv = ServingServer(router, port=0).start()
        yield srv, router
        srv.stop(drain=False, timeout=30)

    def test_acceptance_affinity_failover_metrics(self, served, params):
        srv, router = served
        cl = ServingClient(port=srv.port)
        h = header(21)
        ref = {t: greedy_reference(params, h + [t], 3)
               for t in (1, 2, 3, 4, 5, 6)}

        # (a) affinity-routed requests hit the affinity replica's cache
        target = router.affinity_target(h + [1])
        for t in (1, 2, 3):
            out = cl.complete(h + [t], max_tokens=3)
            assert out["state"] == "done" and out["tokens"] == ref[t]
        text = cl.metrics_text()
        assert f'pt_prefix_hit_rate{{replica="{target}"}} ' in text
        hit_line = [ln for ln in text.splitlines()
                    if ln.startswith(
                        f'pt_prefix_hit_rate{{replica="{target}"}}')][0]
        assert float(hit_line.split()[-1]) > 0

        # (b) kill the affinity replica with requests parked on it:
        # they fail over and complete token-identical over live HTTP
        rep = router.replica(target)
        rep.pause()
        results = {}

        def call(t):
            results[t] = cl.complete(h + [t], max_tokens=3)
        threads = [threading.Thread(target=call, args=(t,))
                   for t in (4, 5, 6)]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                rep.stats()["queued"] < 3:
            time.sleep(0.01)
        assert rep.stats()["queued"] == 3
        rep.kill()
        rep.resume()
        for th in threads:
            th.join(timeout=60)
        assert not any(th.is_alive() for th in threads)
        for t in (4, 5, 6):
            assert results[t]["state"] == "done"
            assert results[t]["tokens"] == ref[t], t

        # (c) aggregated /metrics: failover counted, replica labels on
        # per-replica series, router counters flat
        text = cl.metrics_text()
        fo = [ln for ln in text.splitlines()
              if ln.startswith("pt_router_failovers_total ")][0]
        assert float(fo.split()[-1]) >= 1
        for rid in router.replica_ids + [target]:
            assert f'replica="{rid}"' in text
        assert "pt_router_dispatches_total " in text
        assert "pt_router_affinity_hits_total " in text
        # JSON snapshot nests per-replica registries
        snap = cl.metrics()
        assert set(snap["replicas"]) >= set(router.replica_ids)
        # the failover's flight-recorder trail carries trace ids
        fr = cl._json_call("GET", "/debug/flightrecorder")
        evs = [e for e in fr["events"]
               if e.get("kind") == "router.failover"]
        assert evs and all(e.get("trace_id") for e in evs)
        disp = [e for e in fr["events"]
                if e.get("kind") == "router.dispatch"]
        assert disp and all(e.get("trace_id") for e in disp)

    def test_healthz_and_readyz(self, served):
        srv, router = served
        cl = ServingClient(port=srv.port)
        h = cl.healthz()
        assert h["status"] == "ok" and h["replicas_ready"] == 2
        assert set(h["replicas"]) == set(router.replica_ids)
        r = cl.readyz()
        assert r["ready"] is True
        router.pause()
        try:
            # every replica paused -> the pool takes no traffic:
            # readiness flips (503) while liveness stays 200
            with pytest.raises(ServingHTTPError) as ei:
                cl.readyz()
            assert ei.value.status == 503
            # liveness unaffected: a fully paused pool is alive ("ok"),
            # not "draining" — closed means every pump actually exited
            assert cl.healthz()["status"] == "ok"
        finally:
            router.resume()
        assert cl.readyz()["ready"] is True


class TestSchedulerLedger:
    """Satellite: scheduler.stats() monotonic started/completed/failed
    ledger, surfaced on /healthz and /metrics."""

    def test_ledger_counts_lifecycle(self, params):
        from paddle_tpu.serving import RequestScheduler
        eng = make_factory(params)(0)
        sched = RequestScheduler(eng, max_queue=8)
        try:
            sched.submit([1, 2, 3], max_new_tokens=3).result(timeout=60)
            sched.submit([4, 5, 6], max_new_tokens=3).result(timeout=60)
            lg = sched.stats()["requests"]
            assert lg["submitted"] == lg["started"] == 2
            assert lg["completed"] == 2 and lg["failed"] == 0
            # engine death -> failed, monotonic (nothing decrements)
            def boom():
                raise ReplicaKilledError("dead")
            eng.step = boom
            sr = sched.submit([7, 8, 9], max_new_tokens=3)
            with pytest.raises(Exception):
                sr.result(timeout=60)
            lg = sched.stats()["requests"]
            assert lg["failed"] == 1 and lg["submitted"] == 3
            snap = sched.registry.snapshot()
            assert snap["pt_serving_requests_started"]["value"] == 3
            assert snap["pt_serving_requests_failed"]["value"] == 1
        finally:
            sched.shutdown(drain=False, timeout=30)

    def test_ledger_on_http_surfaces(self, params):
        eng = make_factory(params)(0)
        srv = ServingServer(eng, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            cl.complete([1, 5, 9], max_tokens=3)
            lg = cl.healthz()["requests"]
            assert lg["completed"] == 1 and lg["started"] == 1
            text = cl.metrics_text()
            assert "pt_serving_requests_started_total 1" in text
            assert "pt_serving_requests_failed_total 0" in text
        finally:
            srv.stop(drain=True, timeout=30)


class TestReadyz:
    """Satellite: /readyz is readiness (503 while paused/draining),
    /healthz stays liveness."""

    def test_readyz_flips_on_pause_and_drain(self, params):
        eng = make_factory(params)(0)
        srv = ServingServer(eng, port=0).start()
        cl = ServingClient(port=srv.port)
        try:
            assert cl.readyz()["ready"] is True
            srv.scheduler.pause()
            with pytest.raises(ServingHTTPError) as ei:
                cl.readyz()
            assert ei.value.status == 503
            assert ei.value.body["detail"] == "paused"
            assert cl.healthz()["status"] == "ok"   # still alive
            srv.scheduler.resume()
            assert cl.readyz()["ready"] is True
        finally:
            srv.stop(drain=True, timeout=30)


class TestClientConnRetries:
    """Satellite: bounded client retries now also cover idempotent
    connection-refused/reset before the first streamed byte."""

    def _flaky_conn(self, client, fail, exc):
        calls = {"n": 0}

        def fn(method, path, body=None):
            calls["n"] += 1
            if calls["n"] <= fail:
                raise exc
            return {"ok": True}
        client._json_call = fn
        return calls

    def test_refused_retried_then_succeeds(self, monkeypatch):
        from paddle_tpu.serving import client as C
        sleeps = []
        monkeypatch.setattr(C.time, "sleep", sleeps.append)
        cl = ServingClient(retries=3)
        calls = self._flaky_conn(cl, 2, ConnectionRefusedError(
            "connection refused"))
        assert cl.complete([1, 2])["ok"] is True
        assert calls["n"] == 3 and len(sleeps) == 2

    def test_reset_retried(self, monkeypatch):
        from paddle_tpu.serving import client as C
        monkeypatch.setattr(C.time, "sleep", lambda s: None)
        cl = ServingClient(retries=1)
        calls = self._flaky_conn(cl, 1, ConnectionResetError("reset"))
        assert cl.complete([1, 2])["ok"] is True
        assert calls["n"] == 2

    def test_exhausted_reraises(self, monkeypatch):
        from paddle_tpu.serving import client as C
        monkeypatch.setattr(C.time, "sleep", lambda s: None)
        cl = ServingClient(retries=2)
        calls = self._flaky_conn(cl, 99, ConnectionRefusedError("no"))
        with pytest.raises(ConnectionRefusedError):
            cl.complete([1, 2])
        assert calls["n"] == 3

    def test_default_no_conn_retry(self):
        cl = ServingClient()
        calls = self._flaky_conn(cl, 99, ConnectionRefusedError("no"))
        with pytest.raises(ConnectionRefusedError):
            cl.complete([1, 2])
        assert calls["n"] == 1

    def test_rolling_restart_invisible_with_retries(self, params):
        """Real sockets: the server goes away and comes back on the
        same port; a client with retries rides through the refused
        connections (what a rolling replica restart looks like from
        outside the router)."""
        eng = make_factory(params)(0)
        srv = ServingServer(eng, port=0).start()
        port = srv.port
        cl = ServingClient(port=port, timeout=10, retries=8,
                           retry_cap_s=0.2)
        assert cl.complete([1, 2, 3], max_tokens=2)["state"] == "done"
        srv.stop(drain=True, timeout=30)

        def restart():
            time.sleep(0.3)
            eng2 = make_factory(params)(0)
            srv2 = ServingServer(eng2, host="127.0.0.1", port=port)
            srv2.start()
            results["srv"] = srv2
        results = {}
        th = threading.Thread(target=restart)
        th.start()
        try:
            out = cl.complete([1, 2, 3], max_tokens=2)
            assert out["state"] == "done"
        finally:
            th.join(timeout=30)
            if "srv" in results:
                results["srv"].stop(drain=True, timeout=30)
