"""paddle.distributed.rpc parity (reference python/paddle/distributed/
rpc/rpc.py): named-worker function RPC over the TCPStore rendezvous.

In-process tests drive two RpcAgent instances directly (the internals
are instantiable precisely for this); the subprocess test exercises the
real cross-process path end to end (children import the full package,
so their startup is jax-import-heavy — hence the generous timeout).
"""
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

import paddle_tpu.distributed.rpc as rpc
from paddle_tpu.distributed.rpc import RpcAgent, _TCPStore

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# module-level so pickle ships them by reference
def _add(a, b):
    return a + b


def _sleep_then(x, secs):
    time.sleep(secs)
    return x


def _fail(msg):
    raise RuntimeError(msg)


class TestTCPStore:
    def test_set_get_add(self):
        port = _free_port()
        master = _TCPStore("127.0.0.1", port, True, timeout=10)
        client = _TCPStore("127.0.0.1", port, False, timeout=10)
        try:
            client.set("k", {"a": 1})
            assert master.get("k") == {"a": 1}
            assert client.add("n", 2) == 2
            assert master.add("n", 3) == 5
            assert client.get("n") == 5
        finally:
            master.stop()

    def test_get_blocks_until_set(self):
        port = _free_port()
        master = _TCPStore("127.0.0.1", port, True, timeout=10)
        try:
            out = {}

            def waiter():
                out["v"] = master.get("late")

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.2)
            assert "v" not in out          # still blocked
            master.set("late", 7)
            t.join(timeout=5)
            assert out["v"] == 7
        finally:
            master.stop()

    def test_get_timeout_raises(self):
        port = _free_port()
        master = _TCPStore("127.0.0.1", port, True, timeout=10)
        try:
            with pytest.raises(TimeoutError):
                master.get("never", timeout=0.4)
        finally:
            master.stop()


def _two_agents(port):
    store0 = _TCPStore("127.0.0.1", port, True, timeout=30)
    store1 = _TCPStore("127.0.0.1", port, False, timeout=30)
    out = {}

    def boot(rank, store):
        out[rank] = RpcAgent(f"w{rank}", rank, 2, store)

    # both constructors barrier on each other -> bring up concurrently
    t = threading.Thread(target=boot, args=(1, store1))
    t.start()
    boot(0, store0)
    t.join(timeout=30)
    return out[0], out[1], store0


class TestRpcAgent:
    def test_sync_async_both_directions(self):
        a0, a1, store = _two_agents(_free_port())
        try:
            assert a0.invoke("w1", _add, (2, 3), None, -1).wait() == 5
            assert a1.invoke("w0", _add, (10, 3), None, -1).wait() == 13
            # self-call works too (reference world_size=1 examples)
            assert a0.invoke("w0", _add, (1, 1), None, -1).wait() == 2
        finally:
            a0.stop(), a1.stop(), store.stop()

    def test_async_overlaps(self):
        """Structural overlap proof, no wall-clock bound (a loaded CI
        box would flake a timing assert): short calls issued AFTER a
        long call complete while it is still in flight."""
        a0, a1, store = _two_agents(_free_port())
        try:
            t0 = time.perf_counter()
            slow = a0.invoke("w1", _sleep_then, ("slow", 2.0), None, -1)
            quick = [a0.invoke("w1", _sleep_then, (i, 0.01), None, -1)
                     for i in range(3)]
            assert [f.wait() for f in quick] == [0, 1, 2]
            if time.perf_counter() - t0 < 1.5:
                # quick calls finished while the long call was still in
                # flight -> they overlapped (guarded so a pathologically
                # slow box can't false-fail the structural check)
                assert not slow._done.is_set()
            assert slow.wait(10) == "slow"
        finally:
            a0.stop(), a1.stop(), store.stop()

    def test_remote_exception_propagates_with_traceback(self):
        a0, a1, store = _two_agents(_free_port())
        try:
            with pytest.raises(RuntimeError, match="kaboom"):
                a0.invoke("w1", _fail, ("kaboom",), None, -1).wait()
            try:
                a0.invoke("w1", _fail, ("kaboom",), None, -1).wait()
            except RuntimeError as e:
                assert "remote traceback" in str(e)
        finally:
            a0.stop(), a1.stop(), store.stop()

    def test_unknown_worker_and_timeout(self):
        a0, a1, store = _two_agents(_free_port())
        try:
            with pytest.raises(ValueError, match="unknown worker"):
                a0.invoke("nope", _add, (1, 2), None, -1).wait()
            with pytest.raises(TimeoutError):
                a0.invoke("w1", _sleep_then, (1, 3.0), None, 0.3).wait()
        finally:
            a0.stop(), a1.stop(), store.stop()

    def test_worker_infos(self):
        a0, a1, store = _two_agents(_free_port())
        try:
            infos = a0.all_worker_infos()
            assert [i.name for i in infos] == ["w0", "w1"]
            assert a0.worker_info().rank == 0
            assert a0.worker_info("w1").rank == 1
            assert infos[1].ip == "127.0.0.1"
        finally:
            a0.stop(), a1.stop(), store.stop()


class TestModuleApi:
    def test_world_size_one_lifecycle(self):
        """reference rpc.py docstring example: single worker, self-call."""
        rpc.init_rpc("solo", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{_free_port()}")
        try:
            assert rpc.rpc_sync("solo", _add, args=(2, 3)) == 5
            fut = rpc.rpc_async("solo", _add, args=(4, 4))
            assert fut.wait() == 8
            me = rpc.get_current_worker_info()
            assert me.name == "solo" and me.rank == 0
            assert rpc.get_all_worker_infos() == [me]
            assert rpc.get_worker_info("solo") == me
        finally:
            rpc.shutdown()
        # shutdown is idempotent and re-init works
        rpc.shutdown()
        rpc.init_rpc("solo2", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{_free_port()}")
        rpc.shutdown()

    def test_uninitialized_raises(self):
        with pytest.raises(RuntimeError, match="init_rpc"):
            rpc.rpc_sync("x", _add, args=(1, 2))

    def test_double_init_raises(self):
        rpc.init_rpc("solo", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{_free_port()}")
        try:
            with pytest.raises(RuntimeError, match="already"):
                rpc.init_rpc("again", rank=0, world_size=1,
                             master_endpoint=f"127.0.0.1:{_free_port()}")
        finally:
            rpc.shutdown()


class TestFrameCapAndTimeout:
    """Oversize frames fail crisply on BOTH ends (RpcFrameError), and
    rpc_sync's wait-forever default becomes bounded fleet-wide via
    PT_RPC_TIMEOUT_S — an explicit timeout argument always wins."""

    def test_send_oversize_refused_before_any_bytes(self, monkeypatch):
        monkeypatch.setattr(rpc, "_MAX_FRAME", 1024)
        a, b = socket.socketpair()
        with a, b:
            with pytest.raises(rpc.RpcFrameError, match="refusing"):
                rpc._send_frame(a, b"x" * 2048)
            # nothing hit the wire: the peer never sees a half-frame
            b.setblocking(False)
            with pytest.raises(BlockingIOError):
                b.recv(1)

    def test_recv_oversize_header_refused_before_alloc(self,
                                                       monkeypatch):
        monkeypatch.setattr(rpc, "_MAX_FRAME", 1024)
        a, b = socket.socketpair()
        with a, b:
            a.sendall(rpc._LEN.pack(4096))
            with pytest.raises(rpc.RpcFrameError, match="claims"):
                rpc._recv_frame(b)

    def test_frame_error_is_connection_error_and_exported(self):
        assert issubclass(rpc.RpcFrameError, ConnectionError)
        assert "RpcFrameError" in rpc.__all__

    def test_env_default_timeout_resolution(self, monkeypatch):
        monkeypatch.delenv("PT_RPC_TIMEOUT_S", raising=False)
        assert rpc._resolve_default_timeout(-1) == -1
        monkeypatch.setenv("PT_RPC_TIMEOUT_S", "2.5")
        assert rpc._resolve_default_timeout(-1) == 2.5
        # explicit timeouts never consult the env
        assert rpc._resolve_default_timeout(7.0) == 7.0
        assert rpc._resolve_default_timeout(None) is None
        monkeypatch.setenv("PT_RPC_TIMEOUT_S", "soon")
        with pytest.raises(ValueError, match="PT_RPC_TIMEOUT_S"):
            rpc._resolve_default_timeout(-1)

    def test_rpc_sync_default_timeout_from_env(self, monkeypatch):
        rpc.init_rpc("solo", rank=0, world_size=1,
                     master_endpoint=f"127.0.0.1:{_free_port()}")
        try:
            monkeypatch.setenv("PT_RPC_TIMEOUT_S", "0.3")
            with pytest.raises(TimeoutError):
                rpc.rpc_sync("solo", _sleep_then, args=(1, 3.0))
            # explicit timeout beats the env default
            assert rpc.rpc_sync("solo", _sleep_then, args=(2, 0.05),
                                timeout=10.0) == 2
            monkeypatch.delenv("PT_RPC_TIMEOUT_S")
            assert rpc.rpc_sync("solo", _sleep_then, args=(3, 0.05)) == 3
        finally:
            rpc.shutdown()


def test_two_process_rpc():
    """The real thing: two processes, rendezvous at the master, calls in
    both directions, remote exception propagation, clean shutdown.

    Retried on EADDRINUSE: the master port is picked by _free_port and
    a sibling test process can grab it in the bind race window."""
    child = os.path.join(HERE, "_rpc_child.py")
    for attempt in range(3):
        port = _free_port()
        procs, outs, errs = [], [], []
        try:
            for rank in range(2):
                env = dict(os.environ,
                           PADDLE_TRAINER_ID=str(rank),
                           PADDLE_MASTER_ENDPOINT=f"127.0.0.1:{port}")
                procs.append(subprocess.Popen(
                    [sys.executable, child], env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True))
            for p in procs:
                # children load rpc.py by file path (stdlib-only, no
                # jax import) so startup is fast even under suite load
                out, err = p.communicate(timeout=120)
                outs.append(out)
                errs.append(err)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if (attempt < 2
                and any("Address already in use" in e for e in errs)):
            continue
        for rank, p in enumerate(procs):
            assert p.returncode == 0, \
                f"rank {rank} failed:\n{errs[rank][-2000:]}"
            assert f"RPC_OK rank={rank}" in outs[rank]
        return


def test_two_process_rpc_with_finish_skew():
    """Rank 1 sprints to shutdown() while rank 0 is still issuing
    module-state calls (get_current_worker_info): the agent must stay
    published through the shutdown barrier. This skew reproduced the
    full-suite 'init_rpc() has not been called' failure
    deterministically before the fix."""
    child = os.path.join(HERE, "_rpc_child.py")
    port = _free_port()
    procs = []
    try:
        for rank in range(2):
            env = dict(os.environ,
                       PADDLE_TRAINER_ID=str(rank),
                       PADDLE_MASTER_ENDPOINT=f"127.0.0.1:{port}",
                       RPC_CHILD_SKEW="1.5")
            procs.append(subprocess.Popen(
                [sys.executable, child], env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        for rank, p in enumerate(procs):
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {rank} failed:\n{err[-2000:]}"
            assert f"RPC_OK rank={rank}" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
