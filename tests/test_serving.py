"""Serving engine: paged continuous-batching decode == dense reference."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import ServingEngine, Request


CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def greedy_reference(params, prompt, n_new):
    """Dense recompute greedy decode (no cache) — ground truth."""
    ids = list(prompt)
    out = []
    for _ in range(n_new):
        logits = M.forward(params, jnp.asarray([ids]), CFG, mesh=None,
                           remat=False)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        ids.append(nxt)
    return out


class TestServing:
    def test_single_request_matches_dense(self, params):
        prompt = [1, 5, 9, 3, 7]
        ref = greedy_reference(params, prompt, 8)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("a", prompt, max_new_tokens=8))
        done = eng.run()
        assert len(done) == 1
        assert done[0].output == ref

    def test_continuous_batching_more_requests_than_slots(self, params):
        prompts = [[1, 2, 3], [9, 8, 7, 6, 5, 4], [11, 12], [13] * 9]
        refs = [greedy_reference(params, p, 6) for p in prompts]
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        for i, p in enumerate(prompts):
            eng.submit(Request(f"r{i}", p, max_new_tokens=6))
        done = eng.run()
        assert len(done) == 4
        by_id = {r.rid: r.output for r in done}
        for i, ref in enumerate(refs):
            assert by_id[f"r{i}"] == ref, f"request {i} diverged"

    def test_page_boundary_crossing(self, params):
        # prompt fills exactly one page; decode crosses into new pages
        prompt = list(range(1, 9))  # len 8 == page_size
        ref = greedy_reference(params, prompt, 10)
        eng = ServingEngine(params, CFG, max_seqs=1, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("b", prompt, max_new_tokens=10))
        done = eng.run()
        assert done[0].output == ref

    def test_eos_stops_early(self, params):
        prompt = [1, 5, 9, 3, 7]
        ref = greedy_reference(params, prompt, 8)
        eos = ref[2]
        stop_at = ref.index(eos)  # eos may repeat earlier in a tiny model
        eng = ServingEngine(params, CFG, max_seqs=1, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("c", prompt, max_new_tokens=8, eos_id=eos))
        done = eng.run()
        assert done[0].output == ref[:stop_at + 1]

    def test_pages_recycled_after_finish(self, params):
        eng = ServingEngine(params, CFG, max_seqs=1, max_seq_len=32,
                            page_size=8, use_pallas=False)
        free0 = len(eng._free)
        for i in range(3):
            eng.submit(Request(f"x{i}", [1, 2, 3, 4], max_new_tokens=4))
        eng.run()
        assert len(eng.finished) == 3
        assert len(eng._free) == free0

    def test_kernel_interpret_path_matches(self, params):
        # decode attention through the pallas kernel (interpret mode)
        prompt = [2, 4, 6]
        ref = greedy_reference(params, prompt, 4)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, interpret=True)
        eng.submit(Request("k", prompt, max_new_tokens=4))
        done = eng.run()
        assert done[0].output == ref

    def test_ragged_batch_prefill_one_call(self, params):
        """All admitted prompts prefill in ONE varlen call (no per-sequence
        dense loop) and still match the dense reference."""
        from paddle_tpu.models import llama_serving as S
        prompts = [[1, 2, 3], [9, 8, 7, 6, 5, 4], [11, 12], [13] * 9]
        refs = [greedy_reference(params, p, 4) for p in prompts]
        calls = {"varlen": 0, "single": 0}
        orig_v, orig_s = S.prefill_varlen, S.prefill

        def spy_v(*a, **k):
            calls["varlen"] += 1
            return orig_v(*a, **k)

        def spy_s(*a, **k):
            calls["single"] += 1
            return orig_s(*a, **k)

        S.prefill_varlen, S.prefill = spy_v, spy_s
        try:
            # bucketed-machinery test: the varlen prefill entry point
            # only runs with the ragged step off
            eng = ServingEngine(params, CFG, max_seqs=4, max_seq_len=64,
                                page_size=8, use_pallas=False,
                                ragged=False)
            for i, p in enumerate(prompts):
                eng.submit(Request(f"r{i}", p, max_new_tokens=4))
            done = eng.run()
        finally:
            S.prefill_varlen, S.prefill = orig_v, orig_s
        assert calls["varlen"] == 1 and calls["single"] == 0
        by_id = {r.rid: r.output for r in done}
        for i, ref in enumerate(refs):
            assert by_id[f"r{i}"] == ref, f"request {i} diverged"

    def test_admission_respects_page_capacity(self, params):
        """Admission must not pop requests it cannot scatter: with pages
        for only some waiting requests, the rest stay queued and finish
        later (no dropped/lost requests)."""
        prompts = [[1, 2, 3, 4, 5, 6]] * 4   # 6+2 tokens fit 1 page (ps=8)
        eng = ServingEngine(params, CFG, max_seqs=4, max_seq_len=16,
                            page_size=8, use_pallas=False)
        # only 2 free pages: capacity admits 2 seqs; the other 2 must stay
        # queued (NOT be popped and lost) until pages free up
        eng._free = eng._free[:2]
        for i, p in enumerate(prompts):
            eng.submit(Request(f"r{i}", p, max_new_tokens=2))
        done = eng.run(max_steps=200)
        assert sorted(r.rid for r in done) == [f"r{i}" for i in range(4)]
        refs = [greedy_reference(params, p, 2) for p in prompts]
        by_id = {r.rid: r.output for r in done}
        for i, ref in enumerate(refs):
            assert by_id[f"r{i}"] == ref


class TestServingRobustness:
    """VERDICT r3 item 8: engine-level admission control, pool
    exhaustion, preemption under pressure, sampling determinism."""

    def test_submit_rejects_over_max_seq_len(self, params):
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=16,
                            page_size=8, use_pallas=False)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.submit(Request("r", list(range(1, 14)), max_new_tokens=8))
        with pytest.raises(ValueError, match="empty prompt"):
            eng.submit(Request("r", [], max_new_tokens=4))
        # exactly at the limit is accepted
        eng.submit(Request("ok", list(range(1, 9)), max_new_tokens=8))
        assert len(eng._waiting) == 1

    def test_ctor_rejects_pool_below_one_sequence(self, params):
        with pytest.raises(ValueError, match="num_pages"):
            ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                          page_size=8, num_pages=4, use_pallas=False)

    def test_oversubscribed_pool_preempts_and_completes(self, params):
        """Pool holds ~1.5 sequences' worst case; two long generations
        must BOTH finish via preemption (default offload policy), with
        outputs identical to the fully-provisioned run (greedy
        determinism across eviction/resume)."""
        prompts = [[1, 5, 9, 3], [2, 6, 4, 8]]
        n_new = 24  # crosses several 8-token page boundaries
        refs = [greedy_reference(params, p, n_new) for p in prompts]
        # worst case per seq: 32 tokens -> 4 pages; pool = 6 + trash
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                            page_size=8, num_pages=7, use_pallas=False)
        for i, p in enumerate(prompts):
            eng.submit(Request(f"r{i}", p, max_new_tokens=n_new))
        done = eng.run(max_steps=500)
        assert sorted(r.rid for r in done) == ["r0", "r1"]
        assert eng.preemptions > 0, "test did not exercise preemption"
        by_id = {r.rid: r.output for r in done}
        for i, ref in enumerate(refs):
            assert by_id[f"r{i}"] == ref, \
                f"r{i} diverged after preemption (preempts=" \
                f"{eng.preemptions})"
        # pool fully recycled
        assert len(eng._free) == 6

    def test_single_sequence_pool_exhaustion_raises_clearly(self, params):
        """With one active sequence and nothing to preempt, exhaustion
        must surface as the engine-level error, not an allocator
        stack."""
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                            page_size=8, num_pages=5, use_pallas=False)
        eng.submit(Request("r", [1, 2, 3, 4, 5, 6], max_new_tokens=26))
        eng._free = eng._free[:1]  # artificially shrink below growth need
        with pytest.raises(RuntimeError, match="pool exhausted"):
            eng.run(max_steps=200)

    def test_preempted_sampled_request_keeps_its_tokens(self, params):
        """A temperature>0 request preempted mid-generation must resume
        WITHOUT re-sampling already-emitted tokens: same seed ==> same
        output as an unpressured engine."""
        prompt = [3, 7, 2, 9]
        n_new = 20
        outs = []
        for num_pages in (None, 7):  # roomy vs oversubscribed
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                                page_size=8, num_pages=num_pages,
                                use_pallas=False)
            eng.submit(Request("s", prompt, max_new_tokens=n_new,
                               temperature=0.8, top_k=8, seed=123))
            eng.submit(Request("g", [1, 4, 6, 2], max_new_tokens=n_new))
            done = eng.run(max_steps=500)
            outs.append({r.rid: r.output for r in done})
        # the greedy request is deterministic either way; the sampled
        # one must also match because resume never re-picks
        assert outs[0]["g"] == outs[1]["g"]
        assert outs[0]["s"] == outs[1]["s"]


class TestServingSampling:
    def test_temperature_zero_equals_greedy(self, params):
        prompt = [1, 5, 9, 3, 7]
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("g", prompt, max_new_tokens=6, temperature=0.0))
        ref = greedy_reference(params, prompt, 6)
        assert eng.run()[0].output == ref

    def test_sampled_decode_seeded_and_valid(self, params):
        prompt = [2, 4, 6]
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("s1", prompt, max_new_tokens=8, temperature=0.8,
                           top_k=8, top_p=0.9, seed=123))
        eng.submit(Request("s2", prompt, max_new_tokens=8, temperature=0.8,
                           top_k=8, top_p=0.9, seed=123))
        done = {r.rid: r for r in eng.run()}
        # same seed + same prompt → identical stochastic decode
        assert done["s1"].output == done["s2"].output
        assert all(0 <= t < CFG.vocab_size for t in done["s1"].output)

    def test_mixed_greedy_and_sampled_batch(self, params):
        prompt = [1, 2, 3]
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("g", prompt, max_new_tokens=5, temperature=0.0))
        eng.submit(Request("s", prompt, max_new_tokens=5, temperature=1.0,
                           seed=7))
        done = {r.rid: r for r in eng.run()}
        assert done["g"].output == greedy_reference(params, prompt, 5)
        assert len(done["s"].output) == 5

    def test_huge_top_k_clamped(self, params):
        eng = ServingEngine(params, CFG, max_seqs=1, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("k", [1, 2], max_new_tokens=4, temperature=0.9,
                           top_k=10 ** 6, seed=0))
        done = eng.run()
        assert len(done[0].output) == 4


class TestInt8CacheServing:
    """cache_dtype='int8' (VERDICT r4 item 4): quantized KV pool with
    per-token scales, dequant-in-kernel on read. Reference parity:
    cachekv-quant in phi/kernels/fusion/gpu/block_attn.h."""

    def test_int8_engine_matches_fp_engine_greedy(self, params):
        prompts = [[1, 5, 9, 3, 7], [9, 8, 7, 6, 5, 4]]
        outs = {}
        for tag, kw in (("fp", {}), ("int8", {"cache_dtype": "int8"})):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False, **kw)
            for i, p in enumerate(prompts):
                eng.submit(Request(f"r{i}", p, max_new_tokens=8))
            done = eng.run()
            outs[tag] = {r.rid: r.output for r in done}
        # absmax-per-token int8 KV keeps greedy decode on-trajectory
        # at this scale — token-exact against the fp cache engine
        assert outs["int8"] == outs["fp"]

    def test_int8_pool_bytes_halved(self, params):
        fp = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                           page_size=8, dtype=jnp.bfloat16)
        q = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                          page_size=8, cache_dtype="int8")
        fp_bytes = fp.k_pool.nbytes + fp.v_pool.nbytes
        q_bytes = (q.k_pool.nbytes + q.v_pool.nbytes
                   + q.k_scale.nbytes + q.v_scale.nbytes)
        # head_dim 8 at this tiny config → scales cost 4/8 of the pool;
        # real head dims (64-128) approach 2x. Check the dtype plumbing
        # and that we beat bf16 even in the worst tiny case.
        assert q.k_pool.dtype == jnp.int8
        assert q_bytes < fp_bytes, (q_bytes, fp_bytes)

    def test_int8_with_interpret_kernel(self, params):
        """int8 decode through the pallas kernel (interpret) — the
        in-kernel dequant path an on-chip run would take."""
        prompt = [1, 5, 9, 3, 7]
        ref = greedy_reference(params, prompt, 6)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=True, interpret=True,
                            cache_dtype="int8")
        eng.submit(Request("a", prompt, max_new_tokens=6))
        done = eng.run()
        assert done[0].output == ref

    def test_int8_survives_preemption(self, params):
        """Oversubscribed pool + int8 cache: eviction and re-prefill
        must re-quantize cleanly."""
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                            page_size=8, use_pallas=False,
                            num_pages=6, cache_dtype="int8",
                            preempt_policy="recompute")
        refs = {}
        for i, p in enumerate([[1, 2, 3], [7, 6, 5]]):
            refs[f"r{i}"] = greedy_reference(params, p, 10)
            eng.submit(Request(f"r{i}", p, max_new_tokens=10))
        done = eng.run()
        assert len(done) == 2
        for r in done:
            assert r.output == refs[r.rid]


class TestPreemptOffload:
    """preempt_policy="offload": evicted KV pages swap to host and back
    (reference BlockManager swap-out/swap-in) — zero recompute."""

    def test_bad_policy_rejected(self, params):
        with pytest.raises(ValueError, match="preempt_policy"):
            ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                          page_size=8, preempt_policy="swap")

    def test_offload_matches_and_skips_recompute(self, params):
        """Both policies produce greedy-identical outputs under pool
        pressure, but offload's prefill compute is exactly the original
        prompts — eviction costs no re-prefill."""
        prompts = [[1, 5, 9, 3], [2, 6, 4, 8]]
        n_new = 24
        refs = [greedy_reference(params, p, n_new) for p in prompts]
        outs, prefills = {}, {}
        for pol in ("offload", "recompute"):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                                page_size=8, num_pages=7, use_pallas=False,
                                preempt_policy=pol)
            for i, p in enumerate(prompts):
                eng.submit(Request(f"r{i}", p, max_new_tokens=n_new))
            done = eng.run(max_steps=500)
            assert eng.preemptions > 0, f"{pol}: no preemption exercised"
            assert len(eng._free) == 6, f"{pol}: pool not fully recycled"
            outs[pol] = {r.rid: r.output for r in done}
            prefills[pol] = eng.prefill_tokens
        for i, ref in enumerate(refs):
            assert outs["offload"][f"r{i}"] == ref
            assert outs["recompute"][f"r{i}"] == ref
        assert prefills["offload"] == sum(len(p) for p in prompts), \
            "offload resume must not re-prefill"
        assert prefills["recompute"] > prefills["offload"]

    def test_offload_int8_restores_scales(self, params):
        """Quantized pool offload must round-trip pages AND per-token
        scales; greedy outputs stay identical to the reference."""
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                            page_size=8, use_pallas=False, num_pages=7,
                            cache_dtype="int8", preempt_policy="offload")
        refs = {}
        for i, p in enumerate([[1, 2, 3, 4], [7, 6, 5, 2]]):
            refs[f"r{i}"] = greedy_reference(params, p, 24)
            eng.submit(Request(f"r{i}", p, max_new_tokens=24))
        done = eng.run(max_steps=500)
        assert eng.preemptions > 0
        assert len(done) == 2
        for r in done:
            assert r.output == refs[r.rid]

    def test_offload_sampled_request_keeps_tokens(self, params):
        """temperature>0 + offload: resume re-samples nothing; output
        matches the unpressured engine with the same seed."""
        prompt = [3, 7, 2, 9]
        outs = []
        for num_pages in (None, 7):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                                page_size=8, num_pages=num_pages,
                                use_pallas=False, preempt_policy="offload")
            eng.submit(Request("s", prompt, max_new_tokens=20,
                               temperature=0.8, top_k=8, seed=123))
            eng.submit(Request("g", [1, 4, 6, 2], max_new_tokens=20))
            done = eng.run(max_steps=500)
            outs.append({r.rid: r.output for r in done})
        assert outs[0]["g"] == outs[1]["g"]
        assert outs[0]["s"] == outs[1]["s"]


class TestSpeculativeDecoding:
    """Prompt-lookup speculative decoding (reference: PaddleNLP
    speculative / 'inference with reference'): one verify forward per
    chunk, exact greedy equivalence, fewer device steps on repetitive
    text."""

    def test_prompt_lookup_draft(self):
        from paddle_tpu.models.llama_serving import prompt_lookup_draft
        ctx = [1, 2, 3, 4, 1, 2]
        assert prompt_lookup_draft(ctx, 3, ngram=2) == [3, 4, 1]
        assert prompt_lookup_draft(ctx, 1, ngram=2) == [3]
        assert prompt_lookup_draft([1, 2, 3], 4, ngram=2) == []  # no match
        assert prompt_lookup_draft([5], 4, ngram=2) == []        # too short
        # most RECENT earlier occurrence wins
        ctx2 = [7, 8, 1, 7, 8, 2, 7, 8]
        assert prompt_lookup_draft(ctx2, 2, ngram=2) == [2, 7]

    def test_spec_greedy_exact_match_and_fewer_steps(self, params):
        # a highly repetitive prompt: prompt-lookup drafts well, so the
        # engine must finish in strictly fewer device steps while
        # emitting EXACTLY the plain-decode tokens
        prompt = [3, 9, 4, 3, 9, 4, 3, 9, 4, 3, 9]
        n_new = 16
        ref = greedy_reference(params, prompt, n_new)

        base = ServingEngine(params, CFG, max_seqs=2, max_seq_len=128,
                             page_size=8, use_pallas=False)
        base.submit(Request("p", prompt, max_new_tokens=n_new))
        base.run()
        assert base.finished[0].output == ref

        spec = ServingEngine(params, CFG, max_seqs=2, max_seq_len=128,
                             page_size=8, use_pallas=False, spec_decode=4)
        spec.submit(Request("s", prompt, max_new_tokens=n_new))
        spec.run()
        assert spec.finished[0].output == ref
        assert spec.device_steps < base.device_steps, (
            spec.device_steps, base.device_steps)
        assert spec.spec_accepted > 0

    def test_spec_matches_on_random_prompts(self, params):
        # non-repetitive prompts: drafts often rejected — output must
        # STILL match plain greedy exactly, batch of 3 with different
        # lengths
        rng = np.random.RandomState(7)
        prompts = [list(map(int, rng.randint(0, 64, n)))
                   for n in (5, 11, 8)]
        refs = [greedy_reference(params, p, 10) for p in prompts]
        eng = ServingEngine(params, CFG, max_seqs=3, max_seq_len=128,
                            page_size=8, use_pallas=False, spec_decode=3)
        for i, p in enumerate(prompts):
            eng.submit(Request(f"r{i}", p, max_new_tokens=10))
        eng.run()
        got = {r.rid: r.output for r in eng.finished}
        for i, ref in enumerate(refs):
            assert got[f"r{i}"] == ref, f"request r{i} diverged"

    def test_spec_mixed_with_sampling_and_eos(self, params):
        # sampling requests ride the verify step un-drafted and stay
        # seeded-deterministic; eos mid-chunk stops exactly like plain
        prompt = [2, 4, 2, 4, 2, 4, 2]
        ref = greedy_reference(params, prompt, 12)
        eos = ref[5]
        plain = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                              page_size=8, use_pallas=False)
        plain.submit(Request("g", prompt, max_new_tokens=12, eos_id=eos))
        plain.submit(Request("t", prompt, max_new_tokens=6,
                             temperature=0.8, top_k=8, seed=11))
        plain.run()
        spec = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                             page_size=8, use_pallas=False, spec_decode=4)
        spec.submit(Request("g", prompt, max_new_tokens=12, eos_id=eos))
        spec.submit(Request("t", prompt, max_new_tokens=6,
                            temperature=0.8, top_k=8, seed=11))
        spec.run()
        pg = {r.rid: r.output for r in plain.finished}
        sg = {r.rid: r.output for r in spec.finished}
        assert sg["g"] == pg["g"]          # eos honored mid-chunk
        assert sg["t"] == pg["t"]          # seeded sampling unchanged

    def test_spec_int8_cache(self, params):
        prompt = [3, 9, 4, 3, 9, 4, 3, 9, 4]
        fp = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                           page_size=8, use_pallas=False, spec_decode=4)
        fp.submit(Request("a", prompt, max_new_tokens=8))
        fp.run()
        q = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                          page_size=8, use_pallas=False, spec_decode=4,
                          cache_dtype="int8")
        q.submit(Request("a", prompt, max_new_tokens=8))
        q.run()
        # int8 quant noise may flip a token eventually; prefix must agree
        a, b = fp.finished[0].output, q.finished[0].output
        assert a[:4] == b[:4]

    def test_verify_step_equals_sequential_decode(self, params):
        """Device-level: one verify_step over a 3-token chunk produces
        the same logits trajectory and pool state as 3 decode_steps."""
        from paddle_tpu.models.llama_serving import (decode_step,
                                                     verify_step)
        # bucketed-machinery test: drives verify_step/decode_step
        # directly and needs _admit's seed-at-admission behavior
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=False)
        eng.submit(Request("a", [1, 5, 9, 3], max_new_tokens=8))
        eng._admit()
        chunk = [int(eng._slots[0].next_token), 7, 2]
        # pages for the chunk
        need = -(-(int(eng.lengths[0]) + 3) // eng.page_size)
        while len(eng._seq_pages[0]) < need:
            eng._alloc_pages(0, 1)
        n_tok = jnp.asarray([3, 0], jnp.int32)
        active = jnp.asarray([True, False])
        toks = jnp.asarray([[chunk[0], chunk[1], chunk[2]], [0, 0, 0]],
                           jnp.int64)
        k1, v1, _, _, logits_v = verify_step(
            eng.params, eng.k_pool, eng.v_pool, eng.page_table,
            eng.lengths, toks, n_tok, active, CFG, eng.page_size)

        ks, vs = eng.k_pool, eng.v_pool
        lens = np.array(eng.lengths)   # engine keeps host np state now
        seq_logits = []
        for g in range(3):
            lens[0] += 1
            ks, vs, _, _, lg = decode_step(
                eng.params, ks, vs, eng.page_table, lens,
                jnp.asarray([chunk[g], 0], jnp.int64), active, CFG,
                eng.page_size, use_pallas=False)
            seq_logits.append(lg[0])
        for g in range(3):
            np.testing.assert_allclose(np.asarray(logits_v[0, g]),
                                       np.asarray(seq_logits[g]),
                                       atol=2e-4)
        # trash page (last) holds masked junk by design — exclude it
        np.testing.assert_allclose(np.asarray(k1[:, :, :-1]),
                                   np.asarray(ks[:, :, :-1]), atol=2e-5)
        np.testing.assert_allclose(np.asarray(v1[:, :, :-1]),
                                   np.asarray(vs[:, :, :-1]), atol=2e-5)

    def test_spec_oversubscribed_pool_no_page_leak(self, params):
        """Spec decode + preemption: pool accounting must balance after
        all requests finish (a stale-slot alloc would leak pages)."""
        eng = ServingEngine(params, CFG, max_seqs=3, max_seq_len=64,
                            page_size=8, use_pallas=False, spec_decode=4,
                            num_pages=12)   # < worst case 3*8+1
        prompt = [3, 9, 4, 3, 9, 4, 3, 9]
        for i in range(4):
            eng.submit(Request(f"o{i}", prompt, max_new_tokens=20))
        eng.run()
        assert len(eng.finished) == 4
        ref = greedy_reference(params, prompt, 20)
        for r in eng.finished:
            assert r.output == ref
        # every page back on the free list (trash page never joins)
        assert sorted(eng._free) == list(range(12 - 1))
        assert all(not p for p in eng._seq_pages.values())


class TestChunkedPrefill:
    """Chunked prefill over the verify chunk (reference parity:
    PaddleNLP/vLLM split-fuse): prompts feed G tokens per step so
    decoders never stall behind a long prompt; outputs stay exact."""

    def test_requires_spec(self, params):
        with pytest.raises(ValueError, match="spec_decode"):
            ServingEngine(params, CFG, chunked_prefill=True)

    def test_chunked_matches_dense(self, params):
        prompt = list(np.random.RandomState(3).randint(1, 64, 21))
        ref = greedy_reference(params, prompt, 8)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, spec_decode=4,
                            chunked_prefill=True)
        eng.submit(Request("c", prompt, max_new_tokens=8))
        done = eng.run()
        assert done[0].output == ref
        # prompt fed in ceil(21/4)=6 chunks, all through verify steps
        assert eng.prefill_tokens == 21

    def test_decode_interleaves_with_long_prefill(self, params):
        """A decoding request must EMIT tokens during the very steps a
        long prompt is still chunk-feeding — not merely coexist."""
        short, long = [5, 3], list(np.random.RandomState(4).randint(1, 64, 40))
        ref_s = greedy_reference(params, short, 10)
        ref_l = greedy_reference(params, long, 6)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, spec_decode=4,
                            chunked_prefill=True)
        eng.submit(Request("short", short, max_new_tokens=10))
        eng.step()   # admits short, feeds its first chunk
        eng.submit(Request("long", long, max_new_tokens=6))
        progressed_during_prefill = 0
        for _ in range(40):
            sreq = next((r for r in eng._slots
                         if r is not None and r.rid == "short"), None)
            lreq = next((r for r in eng._slots
                         if r is not None and r.rid == "long"), None)
            before = len(sreq.output) if sreq is not None else None
            mid_prefill = lreq is not None and eng._prefilling(lreq)
            if not eng.step():
                break
            if (before is not None and mid_prefill
                    and sreq.output and len(sreq.output) > before):
                progressed_during_prefill += 1
        got = {r.rid: r.output for r in eng.finished}
        assert got["short"] == ref_s and got["long"] == ref_l
        assert progressed_during_prefill > 0, (
            "short emitted nothing while the long prompt prefilled")

    def test_chunked_with_sampling_and_mixed_batch(self, params):
        prompt = list(np.random.RandomState(5).randint(1, 64, 17))
        plain = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                              page_size=8, use_pallas=False)
        plain.submit(Request("t", prompt, max_new_tokens=5,
                             temperature=0.7, top_k=8, seed=3))
        plain.run()
        chunked = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False,
                                spec_decode=4, chunked_prefill=True)
        chunked.submit(Request("t", prompt, max_new_tokens=5,
                               temperature=0.7, top_k=8, seed=3))
        chunked.run()
        assert chunked.finished[0].output == plain.finished[0].output

    def test_two_long_prompts_small_pool_no_deadlock(self, params):
        """Admission must reserve a chunked prompt's REMAINING pages:
        with a pool that holds only one long prompt, the second queues
        instead of deadlocking mid-prefill (no evictable victim)."""
        long_a = list(np.random.RandomState(8).randint(1, 64, 40))
        long_b = list(np.random.RandomState(9).randint(1, 64, 40))
        refs = {"a": greedy_reference(params, long_a, 4),
                "b": greedy_reference(params, long_b, 4)}
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=48,
                            page_size=8, use_pallas=False, spec_decode=4,
                            chunked_prefill=True, num_pages=10)
        eng.submit(Request("a", long_a, max_new_tokens=4))
        eng.submit(Request("b", long_b, max_new_tokens=4))
        done = eng.run(max_steps=300)
        got = {r.rid: r.output for r in done}
        assert got == refs

    def test_mid_prefill_slot_is_evictable(self, params):
        """Decode growth under pool pressure may evict a mid-prefill
        neighbor; both requests still finish with exact outputs (the
        victim resumes its feed via offload, or re-feeds via
        recompute)."""
        for policy in ("offload", "recompute"):
            deco = list(np.random.RandomState(10).randint(1, 64, 6))
            long_p = list(np.random.RandomState(11).randint(1, 64, 32))
            ref_d = greedy_reference(params, deco, 26)
            ref_l = greedy_reference(params, long_p, 4)
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=40,
                                page_size=8, use_pallas=False,
                                spec_decode=4, chunked_prefill=True,
                                num_pages=7, preempt_policy=policy)
            eng.submit(Request("d", deco, max_new_tokens=26))
            for _ in range(3):
                eng.step()      # d decoding, holds pages
            eng.submit(Request("l", long_p, max_new_tokens=4))
            done = eng.run(max_steps=400)
            got = {r.rid: r.output for r in done}
            assert got["d"] == ref_d, policy
            assert got["l"] == ref_l, policy


class TestSpeculativeSampling:
    """spec_sample=True: drafts for sampled requests accepted by
    rejection sampling — marginally EXACT vs the request's filtered
    sampling distribution."""

    def test_marginal_distribution_exact(self):
        """Empirical check of the core guarantee: whatever the draft
        is, the emitted token at each position ~ p exactly."""
        from paddle_tpu.models.llama_serving import speculative_sample
        rng0 = np.random.RandomState(0)
        V = 6
        p0 = rng0.dirichlet(np.ones(V))
        p1 = rng0.dirichlet(np.ones(V))
        for draft in (int(np.argmax(p0)), int(np.argmin(p0))):
            counts0 = np.zeros(V)
            trials = 40000
            rng = np.random.RandomState(1)
            for _ in range(trials):
                toks, _ = speculative_sample([p0, p1], [draft], rng)
                counts0[toks[0]] += 1
            emp = counts0 / trials
            # first emitted token must follow p0 regardless of draft
            assert np.abs(emp - p0).max() < 0.015, (draft, emp, p0)

    def test_acceptance_advances_multiple_tokens(self):
        from paddle_tpu.models.llama_serving import speculative_sample
        # point-mass rows: drafts matching the mass are always accepted
        V = 4
        rows = [np.eye(V)[1], np.eye(V)[2], np.eye(V)[3]]
        toks, a = speculative_sample(rows, [1, 2], np.random.RandomState(0))
        assert toks == [1, 2, 3] and a == 2

    def test_engine_spec_sample_runs_and_counts(self, params, monkeypatch):
        """Force drafts every step (prompt-lookup hits depend on the
        sampled trajectory, so patch a constant proposal): the
        rejection-sampling path must run, keep the cache bookkeeping
        exact, and stay deterministic for a fixed seed."""
        from paddle_tpu.models import llama_serving as S
        monkeypatch.setattr(S, "prompt_lookup_draft",
                            lambda ctx, G, ngram=2: [7, 9, 11][:G])
        prompt = [2, 4, 2, 4, 2, 4, 2, 4]
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, spec_decode=4,
                            spec_sample=True)
        eng.submit(Request("t", prompt, max_new_tokens=12,
                           temperature=0.6, top_k=8, seed=5))
        done = eng.run()
        out = done[0].output
        assert len(out) == 12 and all(0 <= t < 64 for t in out)
        assert eng.spec_drafted > 0
        # determinism for a fixed seed and engine config
        eng2 = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                             page_size=8, use_pallas=False, spec_decode=4,
                             spec_sample=True)
        eng2.submit(Request("t", prompt, max_new_tokens=12,
                            temperature=0.6, top_k=8, seed=5))
        assert eng2.run()[0].output == out

    def test_flag_gating(self, params):
        with pytest.raises(ValueError, match="spec_decode"):
            ServingEngine(params, CFG, spec_sample=True)
        # without the flag, sampled requests stay trajectory-identical
        # to the plain engine (covered by test_spec_mixed_with_sampling)


class TestLogprobs:
    """Request(logprobs=True): per-emitted-token raw-model logprob
    (reference parity: predictor logprob outputs)."""

    def _manual(self, params, prompt, out):
        """log p(out[i] | prompt+out[:i]) from the dense reference."""
        lps = []
        ids = list(prompt)
        for tok in out:
            logits = np.asarray(M.forward(params, jnp.asarray([ids]), CFG,
                                          mesh=None, remat=False)[0, -1],
                                np.float64)
            x = logits - logits.max()
            lps.append(float(x[tok] - np.log(np.exp(x).sum())))
            ids.append(tok)
        return lps

    def test_greedy_logprobs_match_dense(self, params):
        prompt = [1, 5, 9, 3, 7]
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("a", prompt, max_new_tokens=6, logprobs=True))
        done = eng.run()
        out, lps = done[0].output, done[0].logprobs
        assert len(lps) == len(out) == 6
        np.testing.assert_allclose(lps, self._manual(params, prompt, out),
                                   atol=2e-4)

    def test_spec_logprobs_match_plain(self, params):
        prompt = [3, 9, 4, 3, 9, 4, 3, 9, 4, 3, 9]
        plain = ServingEngine(params, CFG, max_seqs=2, max_seq_len=128,
                              page_size=8, use_pallas=False)
        plain.submit(Request("p", prompt, max_new_tokens=10, logprobs=True))
        plain.run()
        spec = ServingEngine(params, CFG, max_seqs=2, max_seq_len=128,
                             page_size=8, use_pallas=False, spec_decode=4)
        spec.submit(Request("p", prompt, max_new_tokens=10, logprobs=True))
        spec.run()
        assert spec.finished[0].output == plain.finished[0].output
        assert spec.spec_accepted > 0   # the verify path actually ran
        np.testing.assert_allclose(spec.finished[0].logprobs,
                                   plain.finished[0].logprobs, atol=2e-4)

    def test_sampled_logprobs_are_raw_model(self, params):
        prompt = [2, 4, 6, 8]
        eng = ServingEngine(params, CFG, max_seqs=1, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("t", prompt, max_new_tokens=5, temperature=0.9,
                           top_k=8, seed=3, logprobs=True))
        done = eng.run()
        out, lps = done[0].output, done[0].logprobs
        assert len(lps) == 5 and all(lp <= 0.0 for lp in lps)
        np.testing.assert_allclose(lps, self._manual(params, prompt, out),
                                   atol=2e-4)

    def test_disabled_by_default(self, params):
        eng = ServingEngine(params, CFG, max_seqs=1, max_seq_len=32,
                            page_size=8, use_pallas=False)
        eng.submit(Request("a", [1, 2], max_new_tokens=3))
        done = eng.run()
        assert done[0].logprobs is None


class TestTensorParallelServing:
    """TP-sharded engine (VERDICT r4 item 3): weights under megatron
    NamedShardings, KV pool sharded over KV heads, paged kernels under
    shard_map — outputs must match the single-device engine token for
    token (reference: fleet TP under the predictor, mp_layers.py +
    block_multi_head_attention_kernel.cu)."""

    PROMPTS = [[3, 7, 2, 9, 11], [5, 1, 4], [8, 8, 2, 6, 7, 1]]

    def _mesh(self, tp):
        from jax.sharding import Mesh
        return Mesh(np.asarray(jax.devices()[:tp]).reshape(tp), ("tp",))

    def _run(self, params, mesh, **kw):
        eng = ServingEngine(params, CFG, max_seqs=3, max_seq_len=64,
                            page_size=8, use_pallas=False, mesh=mesh, **kw)
        for i, p in enumerate(self.PROMPTS):
            eng.submit(Request(f"r{i}", p, max_new_tokens=10))
        eng.run()
        return {r.rid: r.output for r in eng.finished}

    def test_tp2_greedy_matches_single_device(self, params):
        assert self._run(params, self._mesh(2)) == self._run(params, None)

    def test_tp2_int8_cache_matches(self, params):
        assert self._run(params, self._mesh(2), cache_dtype="int8") == \
            self._run(params, None, cache_dtype="int8")

    def test_tp2_spec_decode_matches(self, params):
        mesh = self._mesh(2)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=128,
                            page_size=8, use_pallas=False, mesh=mesh,
                            spec_decode=4)
        prompt = [3, 9, 4, 3, 9, 4, 3, 9, 4, 3, 9]
        eng.submit(Request("s", prompt, max_new_tokens=16))
        eng.run()
        assert eng.finished[0].output == greedy_reference(params, prompt, 16)
        assert eng.spec_accepted > 0

    def test_tp2_pallas_interpret_kernels(self, params):
        # the shard_map-wrapped pallas kernels (interpret mode off-TPU)
        # agree with the jnp path under the same tp mesh
        mesh = self._mesh(2)
        got = self._run(params, mesh)
        eng = ServingEngine(params, CFG, max_seqs=3, max_seq_len=64,
                            page_size=8, use_pallas=True, interpret=True,
                            mesh=mesh)
        for i, p in enumerate(self.PROMPTS):
            eng.submit(Request(f"r{i}", p, max_new_tokens=10))
        eng.run()
        assert {r.rid: r.output for r in eng.finished} == got

    def test_tp2_offload_preemption(self, params):
        # page pressure under tp: evict (host-gather sharded pages),
        # resume (scatter back) — identical outputs to the unsharded,
        # unpressured engine
        mesh = self._mesh(2)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                            page_size=8, num_pages=5, use_pallas=False,
                            mesh=mesh, preempt_policy="offload")
        eng.submit(Request("a", [3, 7, 2, 9], max_new_tokens=20))
        eng.submit(Request("b", [1, 4, 6, 2], max_new_tokens=20))
        got = {r.rid: r.output for r in eng.run(max_steps=500)}
        assert eng.preemptions > 0
        ref = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                            page_size=8, use_pallas=False)
        ref.submit(Request("a", [3, 7, 2, 9], max_new_tokens=20))
        ref.submit(Request("b", [1, 4, 6, 2], max_new_tokens=20))
        assert got == {r.rid: r.output for r in ref.run(max_steps=500)}

    def test_degenerate_gqa_sharding_rejected(self, params):
        with pytest.raises(ValueError, match="num_key_value_heads"):
            ServingEngine(params, CFG, max_seqs=2, mesh=self._mesh(4))

    def test_dp_only_mesh_is_single_device(self, params):
        # a mesh without a tp axis leaves the engine unsharded
        from jax.sharding import Mesh
        mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("dp",))
        assert self._run(params, mesh) == self._run(params, None)

    def test_tp2_chunked_spec_int8_composition(self, params):
        # the deepest feature stack in one engine: chunked prefill
        # riding the spec verify chunk, int8 KV pool, all tp-sharded —
        # still token-exact vs the single-device engine
        prompt = list(np.random.RandomState(3).randint(1, 64, 21))
        outs = []
        for m in (None, self._mesh(2)):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False, mesh=m,
                                spec_decode=4, chunked_prefill=True,
                                cache_dtype="int8")
            eng.submit(Request("c", prompt, max_new_tokens=10))
            eng.run()
            outs.append(eng.finished[0].output)
        assert outs[0] == outs[1], outs
