"""Device-side sampling + double-buffered pump (ISSUE 8): the
pipelined step loop must be TOKEN-IDENTICAL to the synchronous one —
greedy and seeded sampling both — across every engine mode, and the
one-step-deep pipeline must drain correctly through every slow path
(cancel, TTL expiry, replica kill, _fail_all, preemption)."""
import time

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import (PipelineStall, Request,
                                             ServingEngine)
from paddle_tpu.serving.metrics import MetricsRegistry
from paddle_tpu.serving.replica import Replica
from paddle_tpu.serving.scheduler import (DeadlineExceededError,
                                          RequestScheduler,
                                          SchedulerError)

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def _submit_mixed(eng, n=4, max_new=10):
    """A workload touching both sampler paths: greedy, seeded
    sampling, and logprobs."""
    eng.submit(Request("g0", [1, 5, 9, 3, 7], max_new_tokens=max_new))
    eng.submit(Request("s0", [2, 4, 6], max_new_tokens=max_new,
                       temperature=0.8, top_k=8, top_p=0.9, seed=123))
    eng.submit(Request("g1", [9, 9, 2], max_new_tokens=max_new,
                       logprobs=True))
    eng.submit(Request("s1", [7, 1], max_new_tokens=max_new,
                       temperature=1.1, seed=7, logprobs=True))


def _outputs(done):
    return {r.rid: (list(r.output), None if r.logprobs is None
                    else [round(v, 5) for v in r.logprobs])
            for r in done}


MODES = {
    "plain": {},
    "int8": {"cache_dtype": "int8"},
    "prefix": {"prefix_cache": True},
    "tier": {"prefix_cache": True, "host_tier_bytes": 1 << 20},
    "recompute": {"preempt_policy": "recompute"},
    # spec/chunked fall back to the synchronous loop inside
    # run_pipelined (drafting needs host-current context): the
    # pipelined DRIVER must still give identical tokens
    "spec": {"spec_decode": 4},
    "chunked": {"spec_decode": 4, "chunked_prefill": True},
}
# every mode is covered; the tier-1 budget carries the four that
# exercise distinct code paths (plain carry, quantized scatter,
# shared-page admission, spec fallback) — the remaining three are
# compositions of those and run in the slow lane
_SLOW_MODES = {"tier", "recompute", "chunked"}
_MODE_PARAMS = [pytest.param(m, marks=pytest.mark.slow)
                if m in _SLOW_MODES else m for m in sorted(MODES)]


class TestTokenIdentity:
    """run_pipelined == run, token for token, per engine mode."""

    MODES = MODES

    @pytest.mark.parametrize("mode", _MODE_PARAMS)
    def test_pipelined_equals_sync(self, params, mode):
        kw = self.MODES[mode]
        outs = []
        for pipelined in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False, **kw)
            _submit_mixed(eng)
            done = eng.run_pipelined() if pipelined else eng.run()
            assert len(done) == 4
            outs.append(_outputs(done))
        assert outs[0] == outs[1], f"mode {mode} diverged"

    def test_pipelined_under_preemption(self, params):
        """An oversubscribed pool forces preemption mid-run: the
        pipelined loop must drain (PipelineStall) and still emit the
        unpressured engine's exact tokens."""
        outs = []
        for num_pages in (None, 6):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=32,
                                page_size=8, num_pages=num_pages,
                                use_pallas=False)
            eng.submit(Request("s", [3, 7, 2, 9], max_new_tokens=20,
                               temperature=0.8, top_k=8, seed=123))
            eng.submit(Request("g", [1, 4, 6, 2], max_new_tokens=20))
            done = eng.run_pipelined(max_steps=500)
            outs.append({r.rid: r.output for r in done})
            if num_pages is not None:
                assert eng.preemptions > 0, num_pages
        assert outs[0] == outs[1]

    def test_eos_finish_rolls_back_overrun(self, params):
        """An eos finish is not host-predictable: the pipelined loop
        runs the slot one zombie step past its end, discards that
        token, and the final state (output AND device_steps ledger
        consistency) matches the sync loop."""
        prompt = [2, 4, 2, 4, 2]
        probe = ServingEngine(params, CFG, max_seqs=1, max_seq_len=64,
                              page_size=8, use_pallas=False)
        probe.submit(Request("p", prompt, max_new_tokens=12))
        ref = probe.run()[0].output
        eos = ref[5]
        outs = []
        for pipelined in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False,
                                prefix_cache=True)
            eng.submit(Request("e", prompt, max_new_tokens=12,
                               eos_id=eos))
            eng.submit(Request("g", [9, 8, 7], max_new_tokens=9))
            done = eng.run_pipelined() if pipelined else eng.run()
            outs.append(_outputs(done))
            # prefix-cache indexing after the rollback must agree with
            # the sync loop: pool conservation stays intact
            c = eng.pool.counts()
            assert c["free"] + c["cached"] + c["live"] \
                == eng.num_pages - 1
        assert outs[0] == outs[1]
        assert outs[0]["e"][0][-1] == eos
        assert len(outs[0]["e"][0]) == 6

    def test_max_tokens_finish_has_no_zombie_steps(self, params):
        """Budget-bound finishes are host-predictable: the pipelined
        loop must NOT spend device steps past them (same device-step
        count as sync for an eos-free workload)."""
        counts = []
        for pipelined in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False)
            _submit_mixed(eng)
            (eng.run_pipelined() if pipelined else eng.run())
            counts.append(eng.device_steps)
        assert counts[0] == counts[1]

    def test_max_new_tokens_one(self, params):
        """Admission-time finishes (the request never reaches the
        decode loop) under the pipelined driver."""
        for pipelined in (False, True):
            eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                                page_size=8, use_pallas=False)
            eng.submit(Request("one", [1, 2, 3], max_new_tokens=1))
            eng.submit(Request("two", [4, 5], max_new_tokens=6))
            done = eng.run_pipelined() if pipelined else eng.run()
            assert {r.rid: len(r.output) for r in done} == \
                {"one": 1, "two": 6}

    def test_seeded_sampling_reproducible_across_pumps(self, params):
        """Same seed -> same trajectory, and the scheduler pumps agree
        with the bare engine drivers."""
        ref = None
        for driver in ("run", "run_pipelined", "sched", "sched_pipe"):
            if driver.startswith("sched"):
                eng = ServingEngine(params, CFG, max_seqs=2,
                                    max_seq_len=64, page_size=8,
                                    use_pallas=False)
                sch = RequestScheduler(eng, max_queue=8,
                                       metrics=MetricsRegistry(),
                                       pipeline=driver == "sched_pipe")
                h = sch.submit([2, 4, 6], max_new_tokens=10,
                               temperature=0.8, top_k=8, top_p=0.9,
                               seed=123)
                out = h.result(timeout=60)
                sch.shutdown(drain=True, timeout=30)
            else:
                eng = ServingEngine(params, CFG, max_seqs=2,
                                    max_seq_len=64, page_size=8,
                                    use_pallas=False)
                eng.submit(Request("s", [2, 4, 6], max_new_tokens=10,
                                   temperature=0.8, top_k=8, top_p=0.9,
                                   seed=123))
                out = getattr(eng, driver)()[0].output
            if ref is None:
                ref = out
            assert out == ref, driver


class TestDeviceSampler:
    """The sampler runs INSIDE the jitted step with traced params."""

    def test_no_retrace_across_sampling_params(self, params):
        """Acceptance: changing temperature/top_k/top_p/seed between
        requests must not retrace decode_step (sampling params are
        traced arrays, not static)."""
        from paddle_tpu.observability.compile_telemetry import REGISTRY
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        # same contract for both step entry points: ragged engines
        # dispatch serving.unified_step, bucketed ones decode_step
        fn = "serving.unified_step" if eng.ragged \
            else "serving.decode_step"
        eng.submit(Request("a", [1, 2, 3], max_new_tokens=4,
                           temperature=0.7, top_k=5, seed=1))
        eng.run()
        snap = REGISTRY.snapshot()
        fns = snap.get("functions", snap)
        before = fns[fn]["compiles"]
        for i, kw in enumerate((
                {"temperature": 1.3, "top_k": 50, "top_p": 0.5,
                 "seed": 9},
                {"temperature": 0.0},
                {"temperature": 0.2, "top_p": 0.99, "seed": 2,
                 "logprobs": True})):
            eng.submit(Request(f"r{i}", [4 + i, 2], max_new_tokens=4,
                               **kw))
            eng.run()
        snap = REGISTRY.snapshot()
        fns = snap.get("functions", snap)
        assert fns[fn]["compiles"] == before

    def test_greedy_record_matches_legacy_logits(self, params):
        """decode_step's record must agree with its own logits output:
        argmax(logits) == record token for a greedy slot, and the
        logprob is the raw-model log-softmax at that token."""
        from paddle_tpu.models.llama_serving import decode_step
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("a", [1, 2, 3, 4], max_new_tokens=6))
        eng.step()
        B = eng.max_seqs
        tokens = np.zeros((B,), np.int32)
        tokens[0] = eng._slots[0].next_token
        active = np.zeros((B,), bool)
        active[0] = True
        lengths = eng.lengths.copy()
        lengths[0] += 1
        sample = {"temp": jnp.zeros((B,), jnp.float32),
                  "top_k": jnp.zeros((B,), jnp.int32),
                  "top_p": jnp.ones((B,), jnp.float32),
                  "key": jnp.zeros((B, 2), jnp.uint32),
                  "eos": jnp.full((B,), -1, jnp.int32),
                  "remaining": jnp.full((B,), 5, jnp.int32)}
        _, _, _, _, logits, (tok, done, lp) = decode_step(
            eng.params, eng.k_pool, eng.v_pool,
            jnp.asarray(eng.page_table.copy()), jnp.asarray(lengths),
            jnp.asarray(tokens), jnp.asarray(active), eng.config,
            eng.page_size, use_pallas=False, sample=sample)
        row = np.asarray(logits[0], np.float64)
        assert int(tok[0]) == int(np.argmax(row))
        ref_lp = row[int(tok[0])] - (np.log(np.sum(np.exp(row - row.max())))
                                     + row.max())
        np.testing.assert_allclose(float(lp[0]), ref_lp, atol=2e-4)
        assert not bool(done[0])  # remaining 5, no eos

    def test_done_flag_semantics(self, params):
        from paddle_tpu.models.llama_serving import decode_step
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        eng.submit(Request("a", [1, 2, 3, 4], max_new_tokens=6))
        eng.step()
        B = eng.max_seqs
        tokens = np.zeros((B,), np.int32)
        tokens[0] = eng._slots[0].next_token
        active = np.zeros((B,), bool)
        active[0] = True
        lengths = eng.lengths.copy()
        lengths[0] += 1
        base = {"temp": jnp.zeros((B,), jnp.float32),
                "top_k": jnp.zeros((B,), jnp.int32),
                "top_p": jnp.ones((B,), jnp.float32),
                "key": jnp.zeros((B, 2), jnp.uint32)}
        # remaining == 1 -> done regardless of the token
        out = decode_step(
            eng.params, eng.k_pool, eng.v_pool,
            jnp.asarray(eng.page_table.copy()), jnp.asarray(lengths),
            jnp.asarray(tokens), jnp.asarray(active), eng.config,
            eng.page_size, use_pallas=False,
            sample=dict(base, eos=jnp.full((B,), -1, jnp.int32),
                        remaining=jnp.ones((B,), jnp.int32)))
        tok, done, _ = out[5]
        assert bool(done[0])
        # eos hit -> done even with budget left
        out = decode_step(
            eng.params, eng.k_pool, eng.v_pool,
            jnp.asarray(eng.page_table.copy()), jnp.asarray(lengths),
            jnp.asarray(tokens), jnp.asarray(active), eng.config,
            eng.page_size, use_pallas=False,
            sample=dict(base, eos=tok,
                        remaining=jnp.full((B,), 9, jnp.int32)))
        _, done2, _ = out[5]
        assert bool(done2[0])
        # inactive slots are never done
        assert not bool(done[1]) and not bool(done2[1])


class TestPipelineDraining:
    """Cancel / TTL / kill / _fail_all with one step in flight: no
    lost or duplicated tokens, monotonic ledger, clean engine."""

    def _engine(self, params, **kw):
        kw.setdefault("max_seqs", 2)
        kw.setdefault("max_seq_len", 512)
        kw.setdefault("page_size", 8)
        kw.setdefault("use_pallas", False)
        return ServingEngine(params, CFG, **kw)

    def _ledger_consistent(self, sched):
        st = sched.stats()
        led = st["requests"]
        assert led["submitted"] == (led["completed"] + led["failed"]
                                    + led["cancelled"] + led["expired"]
                                    + st["queued"] + st["inflight"])
        return led

    def test_cancel_with_step_in_flight(self, params):
        eng = self._engine(params)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=True)
        h = sched.submit([1, 2, 3], max_new_tokens=400)
        # stream a few chunks so the pipeline is demonstrably rolling
        got = []
        for chunk in h.stream(timeout=30):
            got.extend(chunk)
            if len(got) >= 4:
                h.cancel()
                break
        for chunk in h.stream(timeout=30):
            got.extend(chunk)
        deadline = time.time() + 15
        while h.state == "running" and time.time() < deadline:
            time.sleep(0.01)
        assert h.state == "cancelled"
        # no lost or duplicated tokens: the stream saw exactly the
        # request's final output
        assert got == h.output
        assert len(set([tuple(got)])) == 1
        assert len(h.output) < 400
        sched.drain(timeout=10)
        assert all(r is None for r in eng._slots)
        assert not eng._live
        led = self._ledger_consistent(sched)
        assert led["cancelled"] == 1
        sched.shutdown(drain=True, timeout=30)

    def test_ttl_expiry_with_step_in_flight(self, params):
        eng = self._engine(params)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=True)
        h = sched.submit([4, 5, 6], max_new_tokens=400, ttl_s=0.25)
        with pytest.raises(DeadlineExceededError):
            h.result(timeout=30)
        assert 0 < len(h.output) < 400
        sched.drain(timeout=10)
        assert not eng._live
        led = self._ledger_consistent(sched)
        assert led["expired"] == 1
        # the engine keeps serving afterwards
        h2 = sched.submit([1, 1, 2], max_new_tokens=5)
        assert len(h2.result(timeout=30)) == 5
        sched.shutdown(drain=True, timeout=30)

    def test_replica_kill_with_step_in_flight(self, params):
        rep = Replica("r0", self._engine(params), pipeline=True)
        h = rep.submit([7, 8, 9], max_new_tokens=400)
        # wait until it is demonstrably mid-decode
        deadline = time.time() + 15
        while not h.output and time.time() < deadline:
            time.sleep(0.01)
        rep.kill()
        with pytest.raises(SchedulerError):
            h.result(timeout=30)
        assert h.state == "failed"
        eng = rep.engine
        assert all(r is None for r in eng._slots)
        assert not eng._live
        # pool conservation after the drain: nothing leaked
        c = eng.pool.counts()
        assert c["free"] + c["cached"] + c["live"] == eng.num_pages - 1
        rep.revive()
        h2 = rep.submit([7, 8, 9], max_new_tokens=5)
        assert len(h2.result(timeout=30)) == 5
        led = self._ledger_consistent(rep.scheduler)
        assert led["failed"] == 1 and led["completed"] == 1
        rep.shutdown(drain=True, timeout=30)

    def test_fail_all_drops_pending_ticket(self, params):
        """An exception from the in-flight step surfaces at the async
        read: _fail_all must clear the ticket and fail the requests
        exactly once."""
        eng = self._engine(params)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=True)
        h = sched.submit([1, 2, 3], max_new_tokens=400)
        deadline = time.time() + 15
        while not h.output and time.time() < deadline:
            time.sleep(0.01)
        boom = RuntimeError("injected mid-pipeline failure")

        def _dead(*a, **k):
            raise boom
        eng.step_launch = _dead
        with pytest.raises(SchedulerError):
            h.result(timeout=30)
        del eng.__dict__["step_launch"]
        assert not eng._live and not eng._waiting
        led = self._ledger_consistent(sched)
        assert led["failed"] == 1
        sched.shutdown(drain=True, timeout=30)

    def test_shutdown_drains_pipeline(self, params):
        eng = self._engine(params)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=True)
        hs = [sched.submit([i + 1, 2], max_new_tokens=20)
              for i in range(4)]
        assert sched.shutdown(drain=True, timeout=60)
        for h in hs:
            assert h.state == "done"
            assert len(h.output) == 20


class TestPipelineMetrics:
    def test_host_gap_and_depth_surfaced(self, params):
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=True)
        hs = [sched.submit([i + 1, 2, 3], max_new_tokens=12)
              for i in range(3)]
        [h.result(timeout=60) for h in hs]
        snap = sched.metrics_snapshot()
        assert snap["pt_step_host_gap_seconds"]["count"] > 0
        assert snap["pt_pipeline_depth"]["value"] == 1
        text = sched.render_prometheus()
        assert "pt_step_host_gap_seconds_bucket" in text
        assert "pt_pipeline_depth" in text
        sched.shutdown(drain=True, timeout=30)

    def test_sync_pump_reports_depth_zero(self, params):
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=False)
        sched.submit([1, 2, 3], max_new_tokens=8).result(timeout=60)
        snap = sched.metrics_snapshot()
        assert snap["pt_pipeline_depth"]["value"] == 0
        assert snap["pt_step_host_gap_seconds"]["count"] > 0
        sched.shutdown(drain=True, timeout=30)

    def test_spec_engine_forces_sync_pump(self, params):
        """spec_decode engines fall back to the synchronous pump even
        with pipeline=True (drafting needs host-current context)."""
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, spec_decode=4)
        sched = RequestScheduler(eng, max_queue=8,
                                 metrics=MetricsRegistry(),
                                 pipeline=True)
        assert sched._pipeline is False
        out = sched.submit([3, 9, 4, 3, 9, 4, 3, 9],
                           max_new_tokens=8).result(timeout=60)
        assert len(out) == 8
        sched.shutdown(drain=True, timeout=30)


def test_ptdump_rolls_up_serving_steps(tmp_path, capsys):
    """tools/ptdump.py must surface the step-loop rollup (step time,
    host gap, pipeline depth) from a flight dump's serving.step
    records."""
    import importlib.util
    import json
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ptdump", os.path.join(root, "tools", "ptdump.py"))
    ptdump = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ptdump)
    doc = {"pid": 1, "dumped_at": 0.0, "reason": "test", "capacity": 16,
           "dropped": 0, "events": [
               {"kind": "serving.step", "ts": 1.0, "step_s": 0.002,
                "host_gap_s": 0.0001, "pipeline_depth": 1},
               {"kind": "serving.step", "ts": 2.0, "step_s": 0.004,
                "host_gap_s": 0.0003, "pipeline_depth": 1}]}
    path = tmp_path / "dump.json"
    path.write_text(json.dumps(doc))
    assert ptdump.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "serving steps: 2 sampled" in out
    assert "avg step 3.00ms" in out
    assert "avg host gap 200us" in out
    assert "pipeline depth 1" in out
