"""paddle_tpu.serving runtime: scheduler admission control, deadlines,
priorities, cancellation, the HTTP frontend (streaming completions,
/healthz, /metrics), and graceful shutdown — all end-to-end in-process
on CPU over a real ServingEngine."""
import json
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import Request, ServingEngine
from paddle_tpu.serving import (BackpressureError, DeadlineExceededError,
                                MetricsRegistry, RequestScheduler,
                                SchedulerClosedError, ServingClient,
                                ServingHTTPError, ServingServer)

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def make_engine(params, max_seqs=2, max_seq_len=64, **kw):
    return ServingEngine(params, CFG, max_seqs=max_seqs,
                         max_seq_len=max_seq_len, page_size=8,
                         use_pallas=False, **kw)


def greedy_reference(params, prompt, n_new):
    ids = list(prompt)
    out = []
    for _ in range(n_new):
        logits = M.forward(params, jnp.asarray([ids]), CFG, mesh=None,
                           remat=False)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        ids.append(nxt)
    return out


class TestEngineCancellation:
    def test_cancel_active_releases_slot_and_pages(self, params):
        eng = make_engine(params)
        a = Request("a", [1, 5, 9], max_new_tokens=30)
        b = Request("b", [2, 4, 6], max_new_tokens=8)
        eng.submit(a)
        eng.submit(b)
        free0 = len(eng._free)
        for _ in range(3):
            eng.step()
        assert eng.cancel(a)
        eng.step()
        assert a in eng.finished and a.cancelled and a.slot is None
        # survivor decodes to the exact greedy reference: cancellation
        # must not corrupt the shared page pool
        done = eng.run()
        by_id = {r.rid: r for r in done}
        assert by_id["b"].output == greedy_reference(params, [2, 4, 6], 8)
        assert len(eng._free) == free0

    def test_cancel_queued_drops_before_prefill(self, params):
        eng = make_engine(params, max_seqs=1)
        a = Request("a", [1, 2, 3], max_new_tokens=6)
        b = Request("b", [7, 8, 9], max_new_tokens=6)
        eng.submit(a)
        eng.step()           # a holds the only slot
        eng.submit(b)
        assert eng.cancel(b)
        assert b in eng.finished and b.output == []
        eng.run()
        assert a.output == greedy_reference(params, [1, 2, 3], 6)


class TestScheduler:
    def test_backpressure_rejects_when_queue_full(self, params):
        eng = make_engine(params)
        sched = RequestScheduler(eng, max_queue=2)
        sched.pause()        # nothing drains: deterministic occupancy
        try:
            sched.submit([1, 2, 3], max_new_tokens=4)
            sched.submit([4, 5, 6], max_new_tokens=4)
            with pytest.raises(BackpressureError):
                sched.submit([7, 8, 9], max_new_tokens=4)
            snap = sched.registry.snapshot()
            assert snap["pt_serving_requests_rejected"]["value"] == 1
            assert snap["pt_serving_queue_depth"]["value"] == 2
        finally:
            sched.resume()
            assert sched.shutdown(drain=True, timeout=30)

    def test_never_fits_rejected_immediately(self, params):
        eng = make_engine(params)
        sched = RequestScheduler(eng, max_queue=4)
        try:
            with pytest.raises(ValueError, match="max_seq_len"):
                sched.submit(list(range(1, 60)), max_new_tokens=30)
        finally:
            sched.shutdown(timeout=30)

    def test_deadline_expires_queued_request(self, params):
        eng = make_engine(params, max_seqs=1)
        sched = RequestScheduler(eng, max_queue=4)
        try:
            # paused pump = the queue genuinely backs up (the warm tiny
            # engine otherwise drains 40 tokens inside the TTL)
            sched.pause()
            long = sched.submit([1, 5, 9], max_new_tokens=12)
            doomed = sched.submit([2, 4, 6], max_new_tokens=4,
                                  ttl_s=0.05)
            with pytest.raises(DeadlineExceededError):
                doomed.result(timeout=30)
            assert doomed.state == "expired" and doomed.output == []
            sched.resume()
            assert long.result(timeout=60) == greedy_reference(
                params, [1, 5, 9], 12)
            assert sched.registry.snapshot()[
                "pt_serving_requests_expired"]["value"] == 1
        finally:
            sched.shutdown(timeout=30)

    def test_deadline_cancels_running_request_mid_flight(self, params):
        eng = make_engine(params, max_seqs=1)
        sched = RequestScheduler(eng, max_queue=4)
        try:
            sr = sched.submit([1, 5, 9], max_new_tokens=61, ttl_s=0.02)
            with pytest.raises(DeadlineExceededError):
                sr.result(timeout=60)
            assert sr.state == "expired"
            # cancelled at a step boundary: partial output, not 61
            assert len(sr.output) < 61
            # the engine slot and its pages were reclaimed
            assert all(r is None for r in eng._slots)
        finally:
            sched.shutdown(timeout=30)

    def test_priority_feeds_high_before_low(self, params):
        eng = make_engine(params, max_seqs=1)
        sched = RequestScheduler(eng, max_queue=8)
        try:
            blocker = sched.submit([1, 2, 3], max_new_tokens=20)
            lo = sched.submit([4, 5, 6], max_new_tokens=4,
                              priority="low")
            hi = sched.submit([7, 8, 9], max_new_tokens=4,
                              priority="high")
            lo.result(timeout=60)
            hi.result(timeout=60)
            blocker.result(timeout=60)
            assert hi.t_first_token < lo.t_first_token
        finally:
            sched.shutdown(timeout=30)

    def test_stream_and_result_agree(self, params):
        eng = make_engine(params)
        sched = RequestScheduler(eng, max_queue=4)
        try:
            sr = sched.submit([1, 5, 9, 3, 7], max_new_tokens=8)
            streamed = [t for chunk in sr.stream(timeout=60)
                        for t in chunk]
            assert streamed == greedy_reference(params, [1, 5, 9, 3, 7], 8)
            assert sr.result(timeout=1) == streamed
        finally:
            sched.shutdown(timeout=30)

    def test_shutdown_drains_in_flight(self, params):
        eng = make_engine(params)
        sched = RequestScheduler(eng, max_queue=8)
        srs = [sched.submit([1 + i, 5, 9], max_new_tokens=12)
               for i in range(4)]
        assert sched.shutdown(drain=True, timeout=60)
        for sr in srs:
            assert sr.state == "done"
            assert len(sr.result(timeout=1)) == 12
        with pytest.raises(SchedulerClosedError):
            sched.submit([1, 2], max_new_tokens=2)

    def test_shutdown_no_drain_cancels(self, params):
        eng = make_engine(params, max_seqs=1)
        sched = RequestScheduler(eng, max_queue=8)
        srs = [sched.submit([1 + i, 5, 9], max_new_tokens=50)
               for i in range(3)]
        assert sched.shutdown(drain=False, timeout=60)
        assert all(sr.state in ("cancelled", "done") for sr in srs)
        assert any(sr.state == "cancelled" for sr in srs)


class TestHTTPServer:
    @pytest.fixture()
    def server(self, params):
        eng = make_engine(params)
        srv = ServingServer(eng, port=0, max_queue=4).start()
        yield srv
        srv.stop(drain=False, timeout=30)

    def test_healthz(self, server):
        cl = ServingClient(port=server.port)
        h = cl.healthz()
        assert h["status"] == "ok" and h["queued"] == 0

    def test_streaming_completion_end_to_end(self, server, params):
        cl = ServingClient(port=server.port)
        events = list(cl.stream_complete([1, 5, 9, 3, 7], max_tokens=8))
        assert events[-1]["done"] and events[-1]["state"] == "done"
        toks = [t for ev in events if "tokens" in ev and not ev.get("done")
                for t in ev["tokens"]]
        assert toks == greedy_reference(params, [1, 5, 9, 3, 7], 8)
        assert toks == events[-1]["tokens"]
        # TTFT got observed and is non-zero
        snap = cl.metrics()
        assert snap["pt_serving_ttft_seconds"]["count"] >= 1
        assert snap["pt_serving_ttft_seconds"]["sum"] > 0

    def test_sampled_completion_with_seed_is_reproducible(self, server):
        cl = ServingClient(port=server.port)
        a = cl.complete([2, 4, 6], max_tokens=8, temperature=0.9, seed=3)
        b = cl.complete([2, 4, 6], max_tokens=8, temperature=0.9, seed=3)
        assert a["tokens"] == b["tokens"] and len(a["tokens"]) == 8

    def test_backpressure_is_429_with_retry_after(self, server):
        server.scheduler.pause()
        cl = ServingClient(port=server.port)
        streams = []
        try:
            # fill the bounded queue (max_queue=4) without blocking:
            # streamed requests return headers before any token
            # the generator is lazy: the POST goes out on first next().
            # Background threads block there (paused pump = no tokens)
            # while the submissions land in the bounded queue.
            for i in range(4):
                s = cl.stream_complete([1 + i, 2, 3], max_tokens=4)
                streams.append(s)
                threading.Thread(target=lambda g=s: next(g, None),
                                 daemon=True).start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if server.scheduler.stats()["queued"] == 4:
                    break
                time.sleep(0.01)
            assert server.scheduler.stats()["queued"] == 4
            with pytest.raises(ServingHTTPError) as ei:
                cl.complete([9, 9, 9], max_tokens=4)
            assert ei.value.status == 429 and ei.value.retriable
            snap = cl.metrics()
            assert snap["pt_serving_requests_rejected"]["value"] >= 1
            assert snap["pt_serving_queue_depth_peak"]["value"] >= 4
        finally:
            server.scheduler.resume()

    def test_deadline_maps_to_504(self, server):
        server.scheduler.pause()
        cl = ServingClient(port=server.port)
        try:
            with pytest.raises(ServingHTTPError) as ei:
                cl.complete([1, 2, 3], max_tokens=4, ttl_s=0.05)
            assert ei.value.status == 504
        finally:
            server.scheduler.resume()

    def test_bad_request_is_400(self, server):
        cl = ServingClient(port=server.port)
        with pytest.raises(ServingHTTPError) as ei:
            cl.complete(list(range(1, 60)), max_tokens=30)
        assert ei.value.status == 400
        with pytest.raises(ServingHTTPError) as ei:
            cl._json_call("POST", "/v1/completions", {"prompt": "text"})
        assert ei.value.status == 400

    def test_metrics_exposition_formats(self, server):
        cl = ServingClient(port=server.port)
        cl.complete([3, 1, 4], max_tokens=4)
        text = cl.metrics_text()
        for series in ("pt_serving_ttft_seconds_bucket{le=",
                       "pt_serving_ttft_seconds_count",
                       "pt_serving_queue_depth",
                       "pt_serving_batch_occupancy",
                       "pt_serving_kv_pages_free",
                       "pt_serving_preemptions_total",
                       "# TYPE pt_serving_ttft_seconds histogram"):
            assert series in text, series
        snap = cl.metrics()      # JSON snapshot API
        assert snap["pt_serving_ttft_seconds"]["count"] >= 1
        assert snap["pt_serving_generated_tokens"]["value"] >= 4
        assert json.loads(json.dumps(snap)) == snap  # JSON-clean

    def test_graceful_shutdown_completes_in_flight_stream(self, params):
        eng = make_engine(params)
        srv = ServingServer(eng, port=0, max_queue=4).start()
        cl = ServingClient(port=srv.port)
        got = {}

        def consume():
            evs = list(cl.stream_complete([1, 5, 9], max_tokens=25))
            got["events"] = evs
        t = threading.Thread(target=consume)
        t.start()
        # wait for the stream to actually start producing
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                not srv.scheduler.stats()["inflight"]:
            time.sleep(0.005)
        assert srv.stop(drain=True, timeout=60)
        t.join(timeout=30)
        assert not t.is_alive()
        assert got["events"][-1]["done"]
        assert got["events"][-1]["state"] == "done"
        assert len(got["events"][-1]["tokens"]) == 25
        # post-shutdown: the port no longer accepts work
        with pytest.raises(Exception):
            cl.healthz()


class TestUsageBlock:
    """OpenAI-style usage accounting on /v1/completions (blocking and
    the final SSE event): prompt/completion/cached token counts."""

    def test_blocking_response_usage(self, params):
        eng = make_engine(params)
        srv = ServingServer(eng, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            out = cl.complete([1, 5, 9, 3, 7], max_tokens=6)
            assert out["usage"] == {"prompt_tokens": 5,
                                    "completion_tokens": 6,
                                    "cached_tokens": 0}
        finally:
            srv.stop(drain=True, timeout=30)

    def test_streaming_final_event_usage(self, params):
        eng = make_engine(params)
        srv = ServingServer(eng, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            events = list(cl.stream_complete([2, 4, 6], max_tokens=5))
            u = events[-1]["usage"]
            assert u["prompt_tokens"] == 3
            assert u["completion_tokens"] == 5 == len(events[-1]["tokens"])
            assert u["cached_tokens"] == 0   # prefix cache off by default
        finally:
            srv.stop(drain=True, timeout=30)


class TestClientRetries:
    """Opt-in bounded retry on 429 backpressure, honoring the server's
    Retry-After hint (BackpressureError.retry_after_s) with jitter."""

    def _flaky(self, client, fail, retry_after=2.0):
        calls = {"n": 0}

        def fn(method, path, body=None):
            calls["n"] += 1
            if calls["n"] <= fail:
                raise ServingHTTPError(429, {"error": "queue full"},
                                       retry_after_s=retry_after)
            return {"ok": True, "calls": calls["n"]}
        client._json_call = fn
        return calls

    def test_retries_sleep_out_retry_after_with_jitter(self, monkeypatch):
        from paddle_tpu.serving import client as C
        sleeps = []
        monkeypatch.setattr(C.time, "sleep", sleeps.append)
        cl = ServingClient(retries=3)
        calls = self._flaky(cl, fail=2, retry_after=2.0)
        assert cl.complete([1, 2])["ok"] is True
        assert calls["n"] == 3 and len(sleeps) == 2
        # hint * jittered factor in [0.5, 1.5)
        assert all(1.0 <= s < 3.0 for s in sleeps), sleeps

    def test_retry_cap_bounds_server_hint(self, monkeypatch):
        from paddle_tpu.serving import client as C
        sleeps = []
        monkeypatch.setattr(C.time, "sleep", sleeps.append)
        cl = ServingClient(retries=1, retry_cap_s=0.5)
        self._flaky(cl, fail=1, retry_after=60.0)
        cl.complete([1, 2])
        assert sleeps and all(s < 0.75 for s in sleeps)

    def test_retries_exhausted_reraises(self, monkeypatch):
        from paddle_tpu.serving import client as C
        monkeypatch.setattr(C.time, "sleep", lambda s: None)
        cl = ServingClient(retries=2)
        calls = self._flaky(cl, fail=99)
        with pytest.raises(ServingHTTPError) as ei:
            cl.complete([1, 2])
        assert ei.value.status == 429 and calls["n"] == 3

    def test_default_is_raise_immediately(self):
        cl = ServingClient()      # retries=0
        calls = self._flaky(cl, fail=99)
        with pytest.raises(ServingHTTPError):
            cl.complete([1, 2])
        assert calls["n"] == 1

    def test_non_429_never_retried(self, monkeypatch):
        from paddle_tpu.serving import client as C
        monkeypatch.setattr(C.time, "sleep", lambda s: None)
        cl = ServingClient(retries=5)
        calls = {"n": 0}

        def fn(method, path, body=None):
            calls["n"] += 1
            raise ServingHTTPError(503, {"error": "draining"})
        cl._json_call = fn
        with pytest.raises(ServingHTTPError):
            cl.complete([1, 2])
        assert calls["n"] == 1

    def test_real_server_hint_parsed(self, params):
        """A real 429 carries Retry-After; the client surfaces it as
        retry_after_s on the error (what the retry loop sleeps on)."""
        eng = make_engine(params)
        srv = ServingServer(eng, port=0, max_queue=1).start()
        srv.scheduler.pause()
        cl = ServingClient(port=srv.port)
        streams = []
        try:
            s = cl.stream_complete([1, 2, 3], max_tokens=4)
            streams.append(s)
            threading.Thread(target=lambda: next(s, None),
                             daemon=True).start()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    srv.scheduler.stats()["queued"] < 1:
                time.sleep(0.01)
            with pytest.raises(ServingHTTPError) as ei:
                cl.complete([9, 9, 9], max_tokens=4)
            assert ei.value.status == 429
            assert ei.value.retry_after_s is not None
            assert ei.value.retry_after_s >= 1.0
        finally:
            srv.scheduler.resume()
            srv.stop(drain=False, timeout=30)


class TestMetricsRegistry:
    def test_counter_gauge_histogram_and_render(self):
        r = MetricsRegistry()
        c = r.counter("x_total_ops", "help text")
        c.inc()
        c.inc(2)
        g = r.gauge("x_depth")
        g.set(3)
        g.set_to_max(2)
        h = r.histogram("x_lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        assert c.value == 3 and g.value == 3
        assert h.count == 3 and abs(h.sum - 5.55) < 1e-9
        assert 0 < h.percentile(50) <= 1.0
        text = r.render_prometheus()
        assert "# HELP x_total_ops help text" in text
        assert 'x_lat_bucket{le="+Inf"} 3' in text
        snap = r.snapshot()
        assert snap["x_lat"]["buckets"]["+Inf"] == 3
        with pytest.raises(ValueError):
            r.gauge("x_total_ops")

    def test_registry_reuse_same_object(self):
        r = MetricsRegistry()
        assert r.counter("a") is r.counter("a")
