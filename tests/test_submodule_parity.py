"""Submodule __all__ parity vs the reference (extends
test_namespace_parity.py, which covers top-level paddle.__all__).

For every reference submodule with a literal __all__, each symbol must
exist on our module. Excluded symbols are hardware-vendor APIs with a
documented out-of-scope decision (none currently — even IPU/PS entries
exist as raising facades).
"""
import ast
import importlib
import os

import pytest

REF = "/root/reference/python/paddle"

MODULES = [
    ("nn/__init__.py", "paddle_tpu.nn"),
    ("nn/functional/__init__.py", "paddle_tpu.nn.functional"),
    ("nn/initializer/__init__.py", "paddle_tpu.nn.initializer"),
    ("nn/utils/__init__.py", "paddle_tpu.nn.utils"),
    ("linalg.py", "paddle_tpu.linalg"),
    ("fft.py", "paddle_tpu.fft"),
    ("signal.py", "paddle_tpu.signal"),
    ("amp/__init__.py", "paddle_tpu.amp"),
    ("autograd/__init__.py", "paddle_tpu.autograd"),
    ("distributed/__init__.py", "paddle_tpu.distributed"),
    ("io/__init__.py", "paddle_tpu.io"),
    ("jit/__init__.py", "paddle_tpu.jit"),
    ("metric/__init__.py", "paddle_tpu.metric"),
    ("optimizer/__init__.py", "paddle_tpu.optimizer"),
    ("optimizer/lr.py", "paddle_tpu.optimizer.lr"),
    ("static/__init__.py", "paddle_tpu.static"),
    ("sparse/__init__.py", "paddle_tpu.sparse"),
    ("vision/__init__.py", "paddle_tpu.vision"),
    ("vision/models/__init__.py", "paddle_tpu.vision.models"),
    ("vision/ops.py", "paddle_tpu.vision.ops"),
    ("vision/transforms/__init__.py", "paddle_tpu.vision.transforms"),
    ("vision/datasets/__init__.py", "paddle_tpu.vision.datasets"),
    ("distribution/__init__.py", "paddle_tpu.distribution"),
    ("geometric/__init__.py", "paddle_tpu.geometric"),
    ("incubate/nn/functional/__init__.py",
     "paddle_tpu.incubate.nn.functional"),
    ("text/__init__.py", "paddle_tpu.text"),
    ("audio/__init__.py", "paddle_tpu.audio"),
    ("audio/functional/__init__.py", "paddle_tpu.audio.functional"),
    ("audio/features/__init__.py", "paddle_tpu.audio.features"),
    ("amp/debugging.py", "paddle_tpu.amp.debugging"),
    ("nn/quant/__init__.py", "paddle_tpu.nn.quant"),
    ("sparse/nn/__init__.py", "paddle_tpu.sparse.nn"),
    ("callbacks.py", "paddle_tpu.callbacks"),
    ("incubate/__init__.py", "paddle_tpu.incubate"),
    ("incubate/nn/__init__.py", "paddle_tpu.incubate.nn"),
    ("hub.py", "paddle_tpu.hub"),
    ("device/__init__.py", "paddle_tpu.device"),
    ("profiler/__init__.py", "paddle_tpu.profiler"),
    ("quantization/__init__.py", "paddle_tpu.quantization"),
    ("distributed/fleet/__init__.py", "paddle_tpu.distributed.fleet"),
]


def _ref_all(relpath):
    tree = ast.parse(open(os.path.join(REF, relpath)).read())
    syms = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    syms += [e.value for e in node.value.elts
                             if isinstance(e, ast.Constant)]
    return syms


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference unavailable")
@pytest.mark.parametrize("rel,ours", MODULES,
                         ids=[m[1] for m in MODULES])
def test_submodule_all_parity(rel, ours):
    syms = _ref_all(rel)
    mod = importlib.import_module(ours)
    missing = [s for s in syms if not hasattr(mod, s)]
    assert not missing, f"{ours} missing {len(missing)}: {missing}"
