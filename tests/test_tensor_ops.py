"""Tensor op numerics vs numpy (SURVEY §4: unit per op family)."""
import numpy as np
import pytest

import paddle_tpu as pt


def np_of(t):
    return np.asarray(t.numpy())


class TestCreation:
    def test_to_tensor_dtypes(self):
        assert pt.to_tensor([1, 2]).dtype == np.dtype("int64")
        assert pt.to_tensor([1.0, 2.0]).dtype == np.dtype("float32")
        assert pt.to_tensor([True]).dtype == np.dtype("bool")
        assert pt.to_tensor([1.0], dtype="float64").dtype == np.dtype("float64")
        assert pt.to_tensor([1.0], dtype=pt.bfloat16).dtype == pt.bfloat16

    def test_factories(self):
        assert pt.zeros([2, 3]).shape == [2, 3]
        assert float(pt.ones([2]).sum()) == 2.0
        assert np.allclose(np_of(pt.full([2, 2], 7)), 7)
        assert np_of(pt.arange(5)).tolist() == [0, 1, 2, 3, 4]
        assert pt.arange(5).dtype == np.dtype("int64")
        assert np.allclose(np_of(pt.linspace(0, 1, 5)), np.linspace(0, 1, 5))
        assert np.allclose(np_of(pt.eye(3)), np.eye(3))

    def test_like_and_tri(self):
        x = pt.randn([3, 3])
        assert np.allclose(np_of(pt.zeros_like(x)), 0)
        assert np.allclose(np_of(pt.tril(x)), np.tril(np_of(x)))
        assert np.allclose(np_of(pt.triu(x, 1)), np.triu(np_of(x), 1))

    def test_meshgrid_diag(self):
        a, b = pt.meshgrid(pt.arange(3), pt.arange(4))
        assert a.shape == [3, 4]
        d = pt.diag(pt.to_tensor([1.0, 2.0, 3.0]))
        assert np.allclose(np_of(d), np.diag([1, 2, 3]))


class TestMath:
    def test_binary_broadcast(self):
        a = pt.to_tensor(np.random.randn(3, 1).astype(np.float32))
        b = pt.to_tensor(np.random.randn(1, 4).astype(np.float32))
        assert np.allclose(np_of(a + b), np_of(a) + np_of(b), atol=1e-6)
        assert np.allclose(np_of(a * b), np_of(a) * np_of(b), atol=1e-6)
        assert np.allclose(np_of(a / (b + 10)), np_of(a) / (np_of(b) + 10),
                           atol=1e-6)

    def test_scalar_promotion(self):
        a = pt.to_tensor([1.0, 2.0])
        assert (a + 1).dtype == np.dtype("float32")
        assert (a * 2.5).dtype == np.dtype("float32")
        i = pt.to_tensor([1, 2])
        assert (i + 1).dtype == np.dtype("int64")

    def test_unary(self):
        x = np.abs(np.random.randn(10).astype(np.float32)) + 0.1
        t = pt.to_tensor(x)
        for name in ["sqrt", "exp", "log", "abs", "sin", "cos", "tanh",
                     "floor", "ceil", "rsqrt", "square", "sign"]:
            ours = np_of(getattr(pt, name)(t))
            ref = getattr(np, name)(x) if hasattr(np, name) else None
            if name == "rsqrt":
                ref = 1.0 / np.sqrt(x)
            if name == "square":
                ref = x * x
            assert np.allclose(ours, ref, atol=1e-5), name

    def test_reductions(self):
        x = np.random.randn(4, 5).astype(np.float32)
        t = pt.to_tensor(x)
        assert np.allclose(float(t.sum()), x.sum(), atol=1e-5)
        assert np.allclose(np_of(pt.mean(t, axis=1)), x.mean(1), atol=1e-6)
        assert np.allclose(np_of(pt.max(t, axis=0)), x.max(0))
        assert np.allclose(np_of(pt.prod(t, axis=1)), x.prod(1), atol=1e-5)
        assert np.allclose(np_of(pt.logsumexp(t)),
                           np.log(np.exp(x).sum()), atol=1e-5)
        assert np.allclose(np_of(pt.std(t, unbiased=False)),
                           x.std(), atol=1e-6)

    def test_cumulative(self):
        x = np.random.randn(3, 4).astype(np.float32)
        t = pt.to_tensor(x)
        assert np.allclose(np_of(pt.cumsum(t, axis=1)), np.cumsum(x, 1), atol=1e-6)
        assert np.allclose(np_of(pt.cumprod(t, dim=0)), np.cumprod(x, 0),
                           atol=1e-6)
        v, i = pt.cummax(t, axis=1)
        assert np.allclose(np_of(v), np.maximum.accumulate(x, 1))

    def test_clip_lerp(self):
        x = pt.to_tensor([-2.0, 0.5, 3.0])
        assert np_of(pt.clip(x, -1, 1)).tolist() == [-1.0, 0.5, 1.0]
        a = pt.to_tensor([0.0, 0.0])
        b = pt.to_tensor([10.0, 20.0])
        assert np_of(pt.lerp(a, b, 0.5)).tolist() == [5.0, 10.0]


class TestLinalg:
    def test_matmul_transpose(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(3, 5).astype(np.float32)
        out = pt.matmul(pt.to_tensor(a), pt.to_tensor(b), transpose_x=True)
        assert np.allclose(np_of(out), a.T @ b, atol=1e-5)

    def test_solve_inv_det(self):
        a = np.random.randn(4, 4).astype(np.float64) + 4 * np.eye(4)
        b = np.random.randn(4, 2).astype(np.float64)
        ta, tb = pt.to_tensor(a), pt.to_tensor(b)
        assert np.allclose(np_of(pt.linalg.solve(ta, tb)), np.linalg.solve(a, b),
                           atol=1e-8)
        assert np.allclose(np_of(pt.linalg.inv(ta)), np.linalg.inv(a), atol=1e-8)
        assert np.allclose(float(pt.linalg.det(ta)), np.linalg.det(a), rtol=1e-6)

    def test_svd_qr_eigh(self):
        a = np.random.randn(5, 3).astype(np.float64)
        u, s, vt = pt.linalg.svd(pt.to_tensor(a))
        assert np.allclose(np_of(u) @ np.diag(np_of(s)) @ np_of(vt), a, atol=1e-8)
        q, r = pt.linalg.qr(pt.to_tensor(a))
        assert np.allclose(np_of(q) @ np_of(r), a, atol=1e-8)
        sym = a.T @ a
        w, v = pt.linalg.eigh(pt.to_tensor(sym))
        assert np.allclose(np_of(v) @ np.diag(np_of(w)) @ np_of(v).T, sym,
                           atol=1e-8)

    def test_norm(self):
        x = np.random.randn(3, 4).astype(np.float32)
        t = pt.to_tensor(x)
        assert np.allclose(float(pt.norm(t)), np.linalg.norm(x), atol=1e-5)
        assert np.allclose(np_of(pt.norm(t, p=1, axis=1)),
                           np.abs(x).sum(1), atol=1e-5)

    def test_einsum(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        b = np.random.randn(2, 4, 5).astype(np.float32)
        out = pt.einsum("bij,bjk->bik", pt.to_tensor(a), pt.to_tensor(b))
        assert np.allclose(np_of(out), np.einsum("bij,bjk->bik", a, b), atol=1e-5)


class TestManipulation:
    def test_reshape_zero_dim(self):
        x = pt.randn([2, 3, 4])
        assert pt.reshape(x, [0, -1]).shape == [2, 12]
        assert pt.reshape(x, [-1]).shape == [24]

    def test_concat_split_stack(self):
        a = pt.randn([2, 3])
        b = pt.randn([2, 3])
        c = pt.concat([a, b], axis=0)
        assert c.shape == [4, 3]
        parts = pt.split(c, 2, axis=0)
        assert np.allclose(np_of(parts[0]), np_of(a))
        parts2 = pt.split(c, [1, -1], axis=0)
        assert parts2[1].shape == [3, 3]
        s = pt.stack([a, b], axis=1)
        assert s.shape == [2, 2, 3]

    def test_gather_scatter(self):
        x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
        idx = pt.to_tensor(np.array([0, 2]))
        g = pt.gather(x, idx, axis=0)
        assert np_of(g).tolist() == [[0, 1, 2], [6, 7, 8]]
        upd = pt.to_tensor(np.ones((2, 3), np.float32))
        s = pt.scatter(x, idx, upd)
        assert np_of(s)[0].tolist() == [1, 1, 1]
        nd = pt.gather_nd(x, pt.to_tensor(np.array([[1, 2], [3, 0]])))
        assert np_of(nd).tolist() == [5.0, 9.0]

    def test_squeeze_expand_tile(self):
        x = pt.randn([1, 3, 1])
        assert pt.squeeze(x).shape == [3]
        assert pt.squeeze(x, axis=0).shape == [3, 1]
        assert pt.unsqueeze(x, [0, 2]).shape == [1, 1, 1, 3, 1]
        assert pt.expand(pt.randn([1, 3]), [4, 3]).shape == [4, 3]
        assert pt.tile(pt.randn([2]), [3]).shape == [6]

    def test_take_put_along_axis(self):
        x = np.random.randn(3, 4).astype(np.float32)
        idx = np.argsort(x, axis=1)
        out = pt.take_along_axis(pt.to_tensor(x), pt.to_tensor(idx), axis=1)
        assert np.allclose(np_of(out), np.take_along_axis(x, idx, 1))

    def test_flip_roll_indexing(self):
        x = pt.to_tensor(np.arange(6).reshape(2, 3))
        assert np_of(pt.flip(x, axis=1)).tolist() == [[2, 1, 0], [5, 4, 3]]
        assert np_of(pt.roll(x, 1, axis=1)).tolist() == [[2, 0, 1], [5, 3, 4]]
        assert np_of(x[0, 1:]).tolist() == [1, 2]
        assert np_of(x[:, -1]).tolist() == [2, 5]

    def test_setitem(self):
        x = pt.zeros([3, 3])
        x[1] = 5.0
        assert np_of(x)[1].tolist() == [5, 5, 5]
        x[0, 0] = pt.to_tensor(2.0)
        assert float(x[0, 0]) == 2.0


class TestLogicSearch:
    def test_comparisons(self):
        a = pt.to_tensor([1.0, 2.0, 3.0])
        b = pt.to_tensor([2.0, 2.0, 2.0])
        assert np_of(a < b).tolist() == [True, False, False]
        assert np_of(a == b).tolist() == [False, True, False]
        assert bool(pt.allclose(a, a))
        assert bool(pt.equal_all(a, a))

    def test_where_nonzero(self):
        x = pt.to_tensor([-1.0, 0.0, 2.0])
        w = pt.where(x > 0, x, pt.zeros_like(x))
        assert np_of(w).tolist() == [0.0, 0.0, 2.0]
        nz = pt.nonzero(x)
        assert np_of(nz).reshape(-1).tolist() == [0, 2]

    def test_sort_topk_unique(self):
        x = pt.to_tensor([3.0, 1.0, 2.0])
        assert np_of(pt.sort(x)).tolist() == [1.0, 2.0, 3.0]
        assert np_of(pt.argsort(x)).tolist() == [1, 2, 0]
        v, i = pt.topk(x, 2)
        assert np_of(v).tolist() == [3.0, 2.0]
        u = pt.unique(pt.to_tensor([1, 1, 2, 3, 3]))
        assert np_of(u).tolist() == [1, 2, 3]

    def test_argmax_median(self):
        x = pt.to_tensor(np.array([[1.0, 5.0, 2.0], [7.0, 0.0, 3.0]]))
        assert np_of(pt.argmax(x, axis=1)).tolist() == [1, 0]
        assert float(pt.median(pt.to_tensor([1.0, 2.0, 3.0]))) == 2.0

    def test_masked_select_searchsorted(self):
        x = pt.to_tensor([1.0, 2.0, 3.0, 4.0])
        m = x > 2
        assert np_of(pt.masked_select(x, m)).tolist() == [3.0, 4.0]
        ss = pt.searchsorted(x, pt.to_tensor([2.5]))
        assert np_of(ss).tolist() == [2]


class TestRandomFFT:
    def test_random_shapes_reproducible(self):
        pt.seed(7)
        a = pt.rand([3, 3])
        pt.seed(7)
        b = pt.rand([3, 3])
        assert np.allclose(np_of(a), np_of(b))
        assert pt.randint(0, 10, [5]).dtype == np.dtype("int64")
        assert sorted(np_of(pt.randperm(5)).tolist()) == [0, 1, 2, 3, 4]

    def test_bernoulli_multinomial(self):
        p = pt.full([100], 1.0)
        assert float(pt.bernoulli(p).sum()) == 100.0
        m = pt.multinomial(pt.to_tensor([0.0, 0.0, 1.0]), 3, replacement=True)
        assert np_of(m).tolist() == [2, 2, 2]

    def test_fft_roundtrip(self):
        x = np.random.randn(16).astype(np.float32)
        X = pt.fft.fft(pt.to_tensor(x).astype("complex64"))
        back = pt.fft.ifft(X)
        assert np.allclose(np_of(back).real, x, atol=1e-5)
