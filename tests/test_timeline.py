"""Request timelines + SLO/goodput plane (ISSUE 14). Acceptance
asserted here:

  * every request carries an append-only host-clock timeline whose
    phase intervals TILE its life (phases sum to e2e exactly — the
    "within 5%" wire check is really a stitching check);
  * the timeline survives crash requeue (PT_FAULTS) and cross-replica
    migration (disagg KVHandoff): one contiguous, monotonic ledger
    with the `requeued` / `handoff_export → migrate` segments present;
  * SLO classes (interactive/batch, defaulting from priority) judge
    at finalize: `pt_slo_{attained,violated}_total` with the violation
    attributed to its dominant phase, goodput vs total tokens;
  * the step-time anomaly sentinel (EWMA + MAD, fed on the pump,
    analyzed on the scrape thread) flags an injected step stall;
  * satellite 1: Histogram percentiles landing in the +Inf bucket
    return the largest finite edge (flagged lower bound), never inf;
  * satellite 2: router /metrics scrapes replicas OUTSIDE the router
    lock and times each into pt_router_scrape_seconds{replica=};
  * the whole plane is observational: token outputs are identical
    with PT_SERVE_TIMELINE=0, and disabling it nulls the timelines.
"""
import threading
import time

import jax.numpy as jnp
import pytest

from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import ServingEngine
from paddle_tpu.serving import (FaultPlan, MetricsRegistry,
                                RequestScheduler, Router, ServingClient,
                                ServingServer, StepAnomalySentinel,
                                Timeline, build_replicas, judge_slo,
                                resolve_slo, slo_targets)

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)
PAGE = 8


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def _engine(params, faults=None, **kw):
    kw.setdefault("max_seqs", 2)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("use_pallas", False)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(params, CFG, faults=faults, **kw)


def assert_tiled(tl, tol=0.05):
    """The stitched ledger's core invariant: monotonic stamps, phases
    summing to end-to-end (exactly by construction; 5% is the wire
    acceptance tolerance)."""
    stamps = [t for _, t in tl.marks]
    assert stamps == sorted(stamps), tl.marks
    total = sum(tl.phases().values())
    assert total == pytest.approx(tl.elapsed(), rel=tol, abs=1e-6), \
        (tl.phases(), tl.elapsed())


# ---------------------------------------------------------------------------
# Timeline unit: phase attribution tiles the request's life
# ---------------------------------------------------------------------------
class TestTimelineUnit:
    def test_phases_tile_preempted_life(self):
        tl = Timeline()
        for name, t in [("submit", 0.0), ("admit", 1.0),
                        ("first_token", 3.0), ("preempted", 4.0),
                        ("resumed", 5.0), ("end", 7.0)]:
            tl.mark(name, t=t)
        assert tl.phases() == {"queued": 1.0, "prefill": 2.0,
                               "decode": 3.0, "preempted": 1.0}
        assert sum(tl.phases().values()) == tl.elapsed() == 7.0
        assert tl.ttft() == 3.0
        assert tl.tpot(tokens=5) == pytest.approx(1.0)
        # decode segments merge across the annotation-only end mark
        assert tl.segments() == [("queued", 0.0, 1.0),
                                 ("prefill", 1.0, 3.0),
                                 ("decode", 3.0, 4.0),
                                 ("preempted", 4.0, 5.0),
                                 ("decode", 5.0, 7.0)]

    def test_resume_before_first_token_is_prefill(self):
        tl = Timeline()
        for name, t in [("submit", 0.0), ("admit", 1.0),
                        ("preempted", 2.0), ("resumed", 3.0),
                        ("first_token", 4.0), ("end", 5.0)]:
            tl.mark(name, t=t)
        assert tl.phases() == {"queued": 1.0, "prefill": 2.0,
                               "preempted": 1.0, "decode": 1.0}

    def test_migration_marks_open_the_right_phases(self):
        # export side: submit/admit/first_token/handoff_export, then
        # the decode side stitches migrate -> admit -> end on top
        tl = Timeline()
        for name, t in [("submit", 0.0), ("admit", 1.0),
                        ("first_token", 2.0), ("handoff_export", 3.0)]:
            tl.mark(name, t=t)
        tl2 = Timeline.from_dict(tl.to_dict())
        for name, t in [("migrate", 4.0), ("admit", 5.0),
                        ("handoff_import", 5.5), ("end", 7.0)]:
            tl2.mark(name, t=t)
        assert tl2.phases() == {"queued": 2.0, "prefill": 1.0,
                                "handoff": 1.0, "decode": 3.0}
        assert sum(tl2.phases().values()) == tl2.elapsed() == 7.0
        # the original is untouched (from_dict copies)
        assert len(tl.marks) == 4

    def test_roundtrip_and_steps(self):
        tl = Timeline()
        tl.mark("submit", t=1.5)
        tl.count("prefill", 3)
        tl.count("decode")
        tl.count("decode")
        d = tl.to_dict()
        back = Timeline.from_dict(d)
        assert back.marks == [("submit", 1.5)]
        assert back.steps == {"prefill": 3, "decode": 2}
        assert Timeline.from_dict(None) is None
        assert Timeline.from_dict({}) is None

    def test_spill_restore_are_annotations(self):
        tl = Timeline()
        for name, t in [("submit", 0.0), ("admit", 1.0),
                        ("first_token", 2.0), ("spill", 2.5),
                        ("restore", 3.0), ("end", 4.0)]:
            tl.mark(name, t=t)
        # annotations never open a phase: decode runs 2.0 -> 4.0
        assert tl.phases() == {"queued": 1.0, "prefill": 1.0,
                               "decode": 2.0}


# ---------------------------------------------------------------------------
# SLO resolution + judgement
# ---------------------------------------------------------------------------
class TestSloUnit:
    def test_resolve_explicit_wins_and_priority_defaults(self):
        assert resolve_slo("batch", "high") == "batch"
        assert resolve_slo(None, "high") == "interactive"
        assert resolve_slo(None, "low") == "batch"
        assert resolve_slo(None, "normal") is None
        with pytest.raises(ValueError):
            resolve_slo("platinum", "normal")

    def test_targets_env_override(self, monkeypatch):
        monkeypatch.setenv("PT_SLO_INTERACTIVE_TTFT_S", "0.25")
        assert slo_targets("interactive") == (0.25, 0.1)
        monkeypatch.delenv("PT_SLO_INTERACTIVE_TTFT_S")
        assert slo_targets("interactive") == (1.0, 0.1)

    def test_judge_attained(self):
        ok, ph = judge_slo("interactive", 0.5, 0.05,
                           {"queued": 0.1, "prefill": 0.4})
        assert ok is True and ph is None

    def test_ttft_miss_blames_dominant_pre_token_phase(self):
        ok, ph = judge_slo("interactive", 5.0, 0.05,
                           {"queued": 4.0, "prefill": 0.9,
                            "decode": 0.1})
        assert ok is False and ph == "queued"
        ok, ph = judge_slo("interactive", 5.0, 0.05,
                           {"queued": 0.2, "handoff": 4.0,
                            "decode": 9.0})
        assert ok is False and ph == "handoff"

    def test_tpot_miss_blames_dominant_post_token_phase(self):
        ok, ph = judge_slo("interactive", 0.5, 2.0,
                           {"queued": 0.1, "prefill": 0.3,
                            "decode": 8.0, "preempted": 1.0})
        assert ok is False and ph == "decode"

    def test_worse_overshoot_picks_the_budget(self):
        # ttft 2x over, tpot 30x over -> tpot budget judges, decode
        # pool wins even though prefill is the biggest phase overall
        ok, ph = judge_slo("interactive", 2.0, 3.0,
                           {"prefill": 10.0, "decode": 1.0,
                            "queued": 0.5})
        assert ok is False and ph == "decode"


# ---------------------------------------------------------------------------
# Satellite 1: histogram percentiles in the overflow bucket
# ---------------------------------------------------------------------------
class TestHistogramOverflow:
    def test_overflow_percentile_is_finite_lower_bound(self):
        reg = MetricsRegistry()
        h = reg.histogram("t_seconds")   # default buckets end at 30s
        for v in (100.0, 200.0, 300.0):
            h.observe(v)
        p99, over = h.percentile_overflow(99)
        assert p99 == 30.0 and over is True
        assert h.percentile(50) == 30.0
        snap = h._snap()
        assert snap["p99"] == 30.0
        assert snap["p99_lower_bound"] is True
        assert snap["p50_lower_bound"] is True

    def test_in_range_percentiles_unflagged(self):
        reg = MetricsRegistry()
        h = reg.histogram("u_seconds")
        for v in (0.01, 0.02, 0.03, 0.04):
            h.observe(v)
        v, over = h.percentile_overflow(50)
        assert over is False and 0.0 < v < 30.0
        snap = h._snap()
        assert "p50_lower_bound" not in snap
        assert "p99_lower_bound" not in snap

    def test_mixed_tail_in_overflow(self):
        reg = MetricsRegistry()
        h = reg.histogram("v_seconds")
        for _ in range(99):
            h.observe(0.01)
        h.observe(1000.0)
        assert h.percentile(50) < 0.1
        p99, over = h.percentile_overflow(100)
        assert p99 == 30.0 and over is True


# ---------------------------------------------------------------------------
# Anomaly sentinel unit
# ---------------------------------------------------------------------------
class TestSentinelUnit:
    def test_spike_fires_after_warmup_and_is_excluded(self):
        s = StepAnomalySentinel(warmup=20, k=8.0, floor_s=0.05)
        for _ in range(25):
            s.note(0.01, 1, 1)
        assert s.scan() == []
        s.note(1.0, 0, 2)
        out = s.scan()
        assert len(out) == 1
        a = out[0]
        assert a["step_s"] == 1.0 and a["decode_slots"] == 2
        assert a["threshold_s"] < 0.1
        # the flagged stall must NOT widen the band for the next one
        s.note(1.0)
        out2 = s.scan()
        assert len(out2) == 1 and out2[0]["mean_s"] < 0.05

    def test_small_wobble_under_floor_never_fires(self):
        s = StepAnomalySentinel(warmup=10, floor_s=0.05)
        for _ in range(30):
            s.note(0.01)
        s.note(0.04)          # +30ms wobble: under the 50ms floor
        assert s.scan() == []

    def test_warmup_suppresses_early_judgement(self):
        s = StepAnomalySentinel(warmup=20)
        s.note(0.01)
        s.note(5.0)           # would be a stall, but baseline too young
        assert s.scan() == []


# ---------------------------------------------------------------------------
# Scheduler integration: the live plane
# ---------------------------------------------------------------------------
class TestSchedulerTimeline:
    def test_lifecycle_slo_and_goodput(self, params):
        sched = RequestScheduler(_engine(params), max_queue=8,
                                 metrics=MetricsRegistry())
        try:
            hi = sched.submit([1, 2, 3, 4], max_new_tokens=6,
                              slo="interactive")
            lo = sched.submit([5, 6, 7, 8, 9], max_new_tokens=4,
                              priority="low")
            o1, o2 = hi.result(timeout=120), lo.result(timeout=120)
            assert hi.slo == "interactive" and lo.slo == "batch"
            for h in (hi, lo):
                tl = h.timeline
                assert tl.has("submit") and tl.has("admit") \
                    and tl.has("first_token") and tl.has("end")
                assert_tiled(tl)
                assert tl.steps.get("prefill", 0) >= 1
                assert tl.steps.get("decode", 0) >= 1
                assert h.slo_attained in (True, False)
            snap = sched.metrics_snapshot()
            total = snap["pt_tokens"]["value"]
            good = snap["pt_goodput_tokens"]["value"]
            assert total == len(o1) + len(o2) == 10
            assert 0 <= good <= total
            n_jud = sum(m["value"] for k, m in snap.items()
                        if k.startswith(("pt_slo_attained{",
                                         "pt_slo_violated{")))
            assert n_jud == 2
            # per-phase latency histograms observed each request once
            assert snap["pt_phase_decode_seconds"]["count"] == 2
            # the recent-requests ring carries the same ledger
            rec = sched.recent_requests(10)
            assert {e["rid"] for e in rec} == {hi.rid, lo.rid}
            for e in rec:
                assert e["state"] == "done" and e["phases"]
                assert sum(e["phases"].values()) == pytest.approx(
                    e["e2e_s"], rel=0.05, abs=1e-6)
        finally:
            sched.shutdown(drain=False, timeout=30)

    def test_forced_violation_attributes_a_phase(self, params,
                                                 monkeypatch):
        monkeypatch.setenv("PT_SLO_INTERACTIVE_TTFT_S", "1e-9")
        sched = RequestScheduler(_engine(params), max_queue=8,
                                 metrics=MetricsRegistry())
        try:
            h = sched.submit([1, 2, 3], max_new_tokens=4,
                             slo="interactive")
            h.result(timeout=120)
            assert h.slo_attained is False
            assert h.violated_phase in ("queued", "prefill",
                                        "handoff", "preempted")
            snap = sched.metrics_snapshot()
            key = ('pt_slo_violated{phase="%s"}' % h.violated_phase)
            assert snap[key]["value"] == 1
            # a violated request's tokens are NOT goodput
            assert snap["pt_goodput_tokens"]["value"] == 0
            assert snap["pt_tokens"]["value"] == 4
        finally:
            sched.shutdown(drain=False, timeout=30)

    def test_plane_off_is_token_identical_and_null(self, params,
                                                   monkeypatch):
        prompts = [[1, 2, 3, 4], [5, 6, 7], [2, 4, 6, 8, 10]]

        def run():
            sched = RequestScheduler(_engine(params), max_queue=8,
                                     metrics=MetricsRegistry())
            try:
                hs = [sched.submit(p, max_new_tokens=5)
                      for p in prompts]
                return [h.result(timeout=120) for h in hs], hs
            finally:
                sched.shutdown(drain=False, timeout=30)

        on_outs, on_hs = run()
        monkeypatch.setenv("PT_SERVE_TIMELINE", "0")
        off_outs, off_hs = run()
        assert on_outs == off_outs
        assert all(h.timeline is not None for h in on_hs)
        assert all(h.timeline is None for h in off_hs)


# ---------------------------------------------------------------------------
# Satellite 3a: stitching across crash requeue
# ---------------------------------------------------------------------------
class TestRequeueStitch:
    def test_requeued_request_has_one_contiguous_timeline(self, params):
        sched = RequestScheduler(
            _engine(params, faults=FaultPlan("step_launch:raise@2")),
            max_queue=8, metrics=MetricsRegistry())
        try:
            sched.pause()
            hs = [sched.submit([1 + i, 5, 9, 3], max_new_tokens=6)
                  for i in range(3)]
            sched.resume()
            outs = [h.result(timeout=120) for h in hs]
            assert all(len(o) == 6 for o in outs)
            requeued = [h for h in hs if h.timeline.has("requeued")]
            assert requeued, "fault at step 2 requeued nobody"
            for h in requeued:
                tl = h.timeline
                assert_tiled(tl)
                assert tl.has("first_token") and tl.has("end")
                # requeue reopens the queued phase mid-life
                assert tl.phases().get("queued", 0.0) > 0.0
            # untouched requests stitched nothing extra
            rec = {e["rid"]: e for e in sched.recent_requests(10)}
            for h in hs:
                assert rec[h.rid]["requeues"] == h._requeues
        finally:
            sched.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# Satellite 3b: stitching across disagg migration
# ---------------------------------------------------------------------------
class TestMigrationStitch:
    def test_migrated_request_has_one_stitched_timeline(self, params):
        reps = build_replicas(lambda i: _engine(params), 2,
                              roles=["prefill", "decode"], max_queue=8)
        router = Router(reps)
        try:
            hs = [router.submit([1 + i, 5, 9, 3, 7], max_new_tokens=6,
                                slo="interactive") for i in range(2)]
            outs = [h.result(timeout=120) for h in hs]
            assert all(len(o) == 6 for o in outs)
            assert reps[0].engine.handoff_exports >= 2
            for h in hs:
                tl = h.timeline     # the decode-side (owning) ledger
                for m in ("submit", "handoff_export", "migrate",
                          "first_token", "end"):
                    assert tl.has(m), (m, tl.marks)
                assert_tiled(tl)
                assert tl.phases().get("handoff", 0.0) > 0.0
                # prefill steps stamped on the EXPORTING side survive
                assert tl.steps.get("prefill", 0) >= 1
            # the decode replica's ring owns the terminal entries; the
            # prefill side closed its half as state="handoff"
            dec = {e["rid"] for e in reps[1].recent_requests(10)
                   if e["state"] == "done"}
            pre = {e["rid"]: e for e in reps[0].recent_requests(10)}
            for h in hs:
                assert h._sr.rid in dec
                assert pre[h.rid]["state"] == "handoff"
        finally:
            router.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# Satellite 2: router scrape discipline + timing gauges
# ---------------------------------------------------------------------------
class TestRouterScrape:
    def test_scrape_gauges_and_aggregated_slo_series(self, params):
        reps = build_replicas(lambda i: _engine(params), 2,
                              max_queue=8)
        router = Router(reps)
        try:
            hs = [router.submit([1 + i, 5, 9], max_new_tokens=4,
                                slo="batch") for i in range(2)]
            for h in hs:
                h.result(timeout=120)
            text = router.render_prometheus()
            for rid in router.replica_ids:
                assert f'pt_router_scrape_seconds{{replica="{rid}"}}' \
                    in text
            assert 'pt_slo_attained_total{' in text
            assert 'pt_goodput_tokens_total{' in text
            # aggregation rewrote each replica's series with its tag
            assert 'slo="batch"' in text and 'replica="' in text
            rec = router.recent_requests(10)
            assert len(rec) == 2
            assert {e["replica"] for e in rec} <= \
                set(router.replica_ids)
            stamps = [e["marks"][-1][1] for e in rec]
            assert stamps == sorted(stamps)
        finally:
            router.shutdown(drain=False, timeout=30)

    def test_slow_replica_scrape_does_not_hold_router_lock(self,
                                                           params):
        reps = build_replicas(lambda i: _engine(params), 2,
                              max_queue=8)
        router = Router(reps)
        try:
            slow = reps[0].scheduler
            orig = slow.render_prometheus
            entered = threading.Event()

            def crawl():
                entered.set()
                time.sleep(0.5)
                return orig()
            slow.render_prometheus = crawl
            t = threading.Thread(target=router.render_prometheus)
            t.start()
            assert entered.wait(5)
            t0 = time.perf_counter()
            with router._lock:      # TPL004: scrape happens outside
                pass
            waited = time.perf_counter() - t0
            t.join(10)
            assert waited < 0.25, \
                f"router lock held through a {waited:.2f}s scrape"
        finally:
            router.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# Anomaly sentinel on a live engine: injected step stall
# ---------------------------------------------------------------------------
class TestAnomalyLive:
    def test_injected_delay_fires_sentinel(self, params):
        from paddle_tpu.observability import flight_recorder as _flight
        sched = RequestScheduler(
            _engine(params, faults=FaultPlan(
                "step_launch:delay@30:delay=0.5")),
            max_queue=4, metrics=MetricsRegistry())
        try:
            h = sched.submit([1, 2, 3, 4], max_new_tokens=45)
            out = h.result(timeout=180)
            assert len(out) == 45
            snap = sched.metrics_snapshot()   # scan runs on scrape
            assert snap["pt_step_anomalies"]["value"] >= 1, snap.get(
                "pt_step_anomalies")
            evs = _flight.snapshot()["events"]
            stalls = [e for e in evs
                      if e.get("kind") == "anomaly.step_stall"]
            assert stalls
            a = stalls[-1]
            assert a["step_s"] > a["threshold_s"] > a["mean_s"] > 0
        finally:
            sched.shutdown(drain=False, timeout=30)


# ---------------------------------------------------------------------------
# Acceptance e2e: mixed SLO workload over real HTTP, disagg + crash
# ---------------------------------------------------------------------------
class TestTimelineHTTP:
    def test_acceptance_slo_plane_over_http(self, params, monkeypatch):
        # interactive TTFT target is impossible -> every interactive
        # request violates (attributed to a named phase); batch attains
        monkeypatch.setenv("PT_SLO_INTERACTIVE_TTFT_S", "1e-9")
        # batch must deterministically ATTAIN even on a crawling CI box
        monkeypatch.setenv("PT_SLO_BATCH_TTFT_S", "600")
        monkeypatch.setenv("PT_SLO_BATCH_TPOT_S", "600")
        monkeypatch.setenv("PT_SERVE_TIMING", "1")
        # one injected crash: the decode replica's FIRST device step
        # raises; recovery requeues the migrated victims and finishes
        reps = build_replicas(
            lambda i: _engine(params, max_seqs=4,
                              faults=FaultPlan("step_launch:raise@1")
                              if i == 1 else None),
            2, roles=["prefill", "decode"], max_queue=16)
        router = Router(reps)
        srv = ServingServer(router, port=0).start()
        try:
            cl = ServingClient(port=srv.port, retries=4)
            results = {}

            def call(i, slo):
                results[i] = cl.complete(
                    [1 + i, 5, 9, 3], max_tokens=6, slo=slo)
            threads = [threading.Thread(
                target=call, args=(i, "interactive" if i % 2 else
                                   "batch")) for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 6
            for i, r in results.items():
                assert r["state"] == "done" and len(r["tokens"]) == 6
                tm = r["timing"]    # PT_SERVE_TIMING=1 opt-in block
                assert tm["slo"] in ("interactive", "batch")
                assert sum(tm["phases"].values()) == pytest.approx(
                    tm["e2e_s"], rel=0.05, abs=1e-6)
                if tm["slo"] == "interactive":
                    assert tm["slo_attained"] is False
                    assert tm["violated_phase"] in (
                        "queued", "prefill", "handoff", "preempted")
            # /debug/requests: every completed request, stitched
            dbg = cl.debug_requests(last=50)["requests"]
            done = {e["rid"]: e for e in dbg if e["state"] == "done"}
            assert len(done) == 6
            for e in done.values():
                assert e["replica"] in router.replica_ids
                assert sum(e["phases"].values()) == pytest.approx(
                    e["e2e_s"], rel=0.05, abs=1e-6)
            # /metrics: goodput + SLO counters aggregated with labels
            text = cl.metrics_text()
            att = [ln for ln in text.splitlines()
                   if ln.startswith("pt_slo_attained_total{")]
            vio = [ln for ln in text.splitlines()
                   if ln.startswith("pt_slo_violated_total{")]
            assert att and sum(
                float(ln.rsplit(" ", 1)[1]) for ln in att) >= 3
            assert vio and sum(
                float(ln.rsplit(" ", 1)[1]) for ln in vio) >= 3
            assert any('phase="' in ln for ln in vio)
            good = [ln for ln in text.splitlines()
                    if ln.startswith("pt_goodput_tokens_total{")]
            assert good and sum(
                float(ln.rsplit(" ", 1)[1]) for ln in good) > 0
            assert 'pt_router_scrape_seconds{replica="' in text
        finally:
            srv.stop(drain=False, timeout=30)

    def test_bad_slo_is_a_400(self, params):
        sched = RequestScheduler(_engine(params), max_queue=4,
                                 metrics=MetricsRegistry())
        srv = ServingServer(sched, port=0).start()
        try:
            from paddle_tpu.serving import ServingHTTPError
            cl = ServingClient(port=srv.port)
            with pytest.raises(ServingHTTPError) as ei:
                cl.complete([1, 2, 3], max_tokens=2, slo="platinum")
            assert ei.value.status == 400
            assert "slo" in str(ei.value)
        finally:
            srv.stop(drain=False, timeout=30)

    def test_timing_block_absent_by_default(self, params, monkeypatch):
        monkeypatch.delenv("PT_SERVE_TIMING", raising=False)
        sched = RequestScheduler(_engine(params), max_queue=4,
                                 metrics=MetricsRegistry())
        srv = ServingServer(sched, port=0).start()
        try:
            cl = ServingClient(port=srv.port)
            r = cl.complete([1, 2, 3], max_tokens=2)
            assert "timing" not in r
        finally:
            srv.stop(drain=False, timeout=30)
