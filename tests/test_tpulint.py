"""tpulint unit tests: per-rule positive/negative fixtures, the
suppression grammar, config targeting, and the JSON output schema."""
import json
import textwrap

import pytest

from paddle_tpu.analysis import (DEFAULT_CONFIG, LintConfig, all_rules,
                                 lint_source, render_json)


def run(src, path="paddle_tpu/nn/x.py", config=None, rules=None):
    findings = lint_source(textwrap.dedent(src), path=path,
                           config=config or LintConfig.default(),
                           rules=rules)
    return [f for f in findings if not f.suppressed]


def rule_ids(src, **kw):
    return sorted({f.rule for f in run(src, **kw)})


HOT = LintConfig.default()
HOT.hot_modules = ["hotmod.py"]
HOT.hot_functions = ["Engine.step"]

LOCKED = LintConfig.default()
LOCKED.lock_scope = ["locked_mod.py"]


# ---------------------------------------------------------------- registry
def test_eleven_rules_registered():
    assert [r.id for r in all_rules()] == [
        "TPL001", "TPL002", "TPL003", "TPL004", "TPL005", "TPL006",
        "TPL007", "TPL008", "TPL009", "TPL010", "TPL011"]


# ---------------------------------------------------------------- TPL001
class TestHostSync:
    def test_fires_on_numpy_call_in_jit(self):
        assert rule_ids("""
            import jax
            @jax.jit
            def f(x):
                return x.numpy()
        """) == ["TPL001"]

    def test_fires_on_np_asarray_in_jit(self):
        assert rule_ids("""
            import jax
            import numpy as np
            @jax.jit
            def f(x):
                return np.asarray(x) + 1
        """) == ["TPL001"]

    def test_fires_on_item_and_device_get(self):
        out = run("""
            import jax
            @jax.jit
            def f(x):
                a = x.item()
                return jax.device_get(a)
        """)
        assert [f.rule for f in out] == ["TPL001", "TPL001"]

    def test_fires_on_float_of_traced_param(self):
        assert rule_ids("""
            import jax
            @jax.jit
            def f(x):
                return float(x)
        """) == ["TPL001"]

    def test_silent_on_float_of_shape(self):
        assert rule_ids("""
            import jax
            @jax.jit
            def f(x):
                return x.reshape(int(x.shape[0]) * 2)
        """) == []

    def test_silent_on_jnp_asarray_in_jit(self):
        assert rule_ids("""
            import jax
            import jax.numpy as jnp
            @jax.jit
            def f(x):
                return jnp.asarray(x)
        """) == []

    def test_silent_outside_jit_and_hot_paths(self):
        assert rule_ids("""
            import numpy as np
            def f(x):
                return np.asarray(x)
        """) == []

    def test_fires_in_configured_hot_function(self):
        assert rule_ids("""
            class Engine:
                def step(self):
                    return self.logits.numpy()
        """, path="hotmod.py", config=HOT) == ["TPL001"]

    def test_silent_in_non_hot_function_of_hot_module(self):
        assert rule_ids("""
            class Engine:
                def debug_dump(self):
                    return self.logits.numpy()
        """, path="hotmod.py", config=HOT) == []

    def test_detects_jit_via_wrapping_call(self):
        assert rule_ids("""
            import jax
            def step(x):
                return x.numpy()
            fast_step = jax.jit(step)
        """) == ["TPL001"]


# ---------------------------------------------------------------- TPL002
class TestRetrace:
    def test_fires_on_shape_branch(self):
        assert "TPL002" in rule_ids("""
            import jax
            @jax.jit
            def f(x):
                if x.shape[0] > 4:
                    return x
                return -x
        """)

    def test_fires_on_traced_value_branch(self):
        assert "TPL002" in rule_ids("""
            import jax
            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
        """)

    def test_fires_on_shape_range_loop(self):
        assert "TPL002" in rule_ids("""
            import jax
            @jax.jit
            def f(x):
                acc = 0
                for i in range(x.shape[0]):
                    acc = acc + x[i]
                return acc
        """)

    def test_fires_on_fstring_over_traced(self):
        assert "TPL002" in rule_ids("""
            import jax
            @jax.jit
            def f(x):
                name = f"val={x}"
                return x
        """)

    def test_fires_on_mutable_static_arg_default(self):
        assert "TPL002" in rule_ids("""
            import functools
            import jax
            @functools.partial(jax.jit, static_argnames=("cfg",))
            def f(x, cfg=[1, 2]):
                return x
        """)

    def test_silent_on_none_and_isinstance_branches(self):
        assert rule_ids("""
            import jax
            @jax.jit
            def f(x, w=None):
                if w is None:
                    return x
                if isinstance(x, tuple):
                    return x[0]
                return x + w
        """) == []

    def test_silent_on_static_range_loop(self):
        assert rule_ids("""
            import jax
            @jax.jit
            def f(x):
                for i in range(4):
                    x = x + i
                return x
        """) == []

    def test_silent_outside_jit(self):
        assert rule_ids("""
            def f(x):
                if x.shape[0] > 4:
                    return x
                return -x
        """) == []


# ---------------------------------------------------------------- TPL003
class TestUntracedRandom:
    def test_fires_on_np_random_in_jit(self):
        assert rule_ids("""
            import jax
            import numpy as np
            @jax.jit
            def f(x):
                return x + np.random.normal(size=3)
        """) == ["TPL003"]

    def test_fires_on_stdlib_random_in_jit(self):
        assert rule_ids("""
            import random
            import jax
            @jax.jit
            def f(x):
                return x * random.random()
        """) == ["TPL003"]

    def test_silent_on_jax_random(self):
        assert rule_ids("""
            import jax
            @jax.jit
            def f(x, key):
                return x + jax.random.normal(key, x.shape)
        """) == []

    def test_silent_on_np_random_outside_jit(self):
        assert rule_ids("""
            import numpy as np
            def init(shape):
                return np.random.normal(size=shape)
        """) == []


# ---------------------------------------------------------------- TPL004
class TestLockDiscipline:
    def test_fires_on_bare_write_of_locked_attr(self):
        out = run("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                def inc(self):
                    with self._lock:
                        self._n += 1
                def racy(self):
                    self._n = 5
        """, path="locked_mod.py", config=LOCKED)
        assert [f.rule for f in out] == ["TPL004"]
        assert "racy" in out[0].message

    def test_fires_on_engine_step_under_lock(self):
        out = run("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = 0
                def sync(self):
                    with self._lock:
                        self._state = 1
                def bad(self):
                    with self._lock:
                        self.engine.step()
        """, path="locked_mod.py", config=LOCKED)
        assert [f.rule for f in out] == ["TPL004"]
        assert "device step" in out[0].message

    def test_silent_when_disciplined(self):
        assert rule_ids("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                def inc(self):
                    with self._lock:
                        self._n += 1
                def dec(self):
                    with self._lock:
                        self._n -= 1
                def work(self):
                    self.engine.step()
        """, path="locked_mod.py", config=LOCKED) == []

    def test_locked_suffix_convention_counts_as_held(self):
        assert rule_ids("""
            import threading
            class S:
                def __init__(self):
                    self._cond = threading.Condition()
                    self._depth = 0
                def _feed_locked(self):
                    self._depth += 1
                def pump(self):
                    with self._cond:
                        self._feed_locked()
        """, path="locked_mod.py", config=LOCKED) == []

    def test_out_of_scope_module_not_analyzed(self):
        assert rule_ids("""
            import threading
            class S:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                def inc(self):
                    with self._lock:
                        self._n += 1
                def racy(self):
                    self._n = 5
        """, path="paddle_tpu/nn/x.py", config=LOCKED) == []


# ---------------------------------------------------------------- TPL005
class TestEagerBlock:
    def test_fires_in_library_code(self):
        assert rule_ids("""
            def run(x):
                return x.block_until_ready()
        """) == ["TPL005"]

    def test_fires_on_module_level_jax_block(self):
        assert rule_ids("""
            import jax
            def warm(a):
                jax.block_until_ready(a)
        """) == ["TPL005"]

    def test_silent_in_bench_paths(self):
        assert rule_ids("""
            def run(x):
                return x.block_until_ready()
        """, path="bench_models.py") == []


# ---------------------------------------------------------------- TPL006
class TestImportHygiene:
    def test_fires_on_mutable_default(self):
        assert rule_ids("""
            def f(x, acc=[]):
                acc.append(x)
                return acc
        """) == ["TPL006"]

    def test_fires_on_dict_call_default(self):
        assert rule_ids("""
            def f(x, opts=dict()):
                return opts
        """) == ["TPL006"]

    def test_fires_on_module_level_device_alloc(self):
        assert rule_ids("""
            import jax.numpy as jnp
            CACHE = jnp.zeros((8, 8))
        """) == ["TPL006"]

    def test_fires_on_class_level_device_alloc(self):
        assert rule_ids("""
            import jax
            class M:
                KEY = jax.random.key(0)
        """) == ["TPL006"]

    def test_silent_on_none_default_and_lazy_alloc(self):
        assert rule_ids("""
            import jax.numpy as jnp
            def f(x, acc=None):
                if acc is None:
                    acc = []
                return jnp.zeros((8,))
        """) == []

    def test_silent_on_metadata_helpers(self):
        assert rule_ids("""
            import jax.numpy as jnp
            EPS = jnp.finfo(jnp.float32)
        """) == []


# ------------------------------------------------------------ suppressions
class TestSuppressions:
    SRC = """
        import jax
        @jax.jit
        def f(x):
            return x.numpy(){comment}
    """

    def test_same_line_disable(self):
        src = self.SRC.format(
            comment="  # tpulint: disable=TPL001 -- test harness pull")
        findings = lint_source(textwrap.dedent(src))
        assert len(findings) == 1
        assert findings[0].suppressed
        assert findings[0].suppress_reason == "test harness pull"

    def test_disable_next_line(self):
        src = """
            import jax
            @jax.jit
            def f(x):
                # tpulint: disable-next-line=TPL001 -- reviewed
                return x.numpy()
        """
        assert run(src) == []

    def test_disable_file(self):
        src = """
            # tpulint: disable-file=TPL001 -- fixture file
            import jax
            @jax.jit
            def f(x):
                return x.numpy()
        """
        assert run(src) == []

    def test_disable_all_keyword(self):
        src = self.SRC.format(comment="  # tpulint: disable=all")
        assert run(src) == []

    def test_wrong_rule_does_not_silence(self):
        src = self.SRC.format(comment="  # tpulint: disable=TPL005")
        assert rule_ids(src) == ["TPL001"]

    def test_multiple_rules_one_comment(self):
        src = """
            import jax
            import numpy as np
            @jax.jit
            def f(x):
                if x > 0:  # tpulint: disable=TPL002 -- static flag
                    return np.asarray(x)  # tpulint: disable=TPL001 -- reviewed
                return x
        """
        assert run(src) == []


# ------------------------------------------------------------- JSON output
class TestJsonOutput:
    def test_schema(self):
        src = textwrap.dedent("""
            import jax
            @jax.jit
            def f(x):
                return x.numpy()
        """)
        findings = lint_source(src, path="paddle_tpu/nn/x.py")
        doc = json.loads(render_json(findings, files_scanned=1))
        assert set(doc) == {"version", "files_scanned", "findings",
                            "counts", "suppressed", "clean"}
        assert doc["version"] == 1
        assert doc["files_scanned"] == 1
        assert doc["clean"] is False
        assert doc["counts"] == {"TPL001": 1}
        (f,) = doc["findings"]
        assert set(f) == {"rule", "severity", "path", "line", "col",
                          "message", "context"}
        assert f["rule"] == "TPL001"
        assert f["severity"] == "error"
        assert f["path"] == "paddle_tpu/nn/x.py"
        assert f["line"] == 5 and isinstance(f["col"], int)

    def test_clean_and_suppressed_counts(self):
        src = textwrap.dedent("""
            import jax
            @jax.jit
            def f(x):
                return x.numpy()  # tpulint: disable=TPL001 -- ok
        """)
        doc = json.loads(render_json(lint_source(src), files_scanned=1))
        assert doc["clean"] is True
        assert doc["suppressed"] == 1
        assert doc["findings"][0]["suppressed"] is True
        assert doc["findings"][0]["suppress_reason"] == "ok"


# ------------------------------------------------------------------ errors
def test_syntax_error_is_a_finding():
    out = lint_source("def f(:\n", path="broken.py")
    assert out[0].rule == "TPL000"
    assert out[0].severity.value == "error"


def test_severity_override_via_config():
    cfg = LintConfig.default()
    cfg.severity = {"TPL001": "info"}
    src = """
        import jax
        @jax.jit
        def f(x):
            return x.numpy()
    """
    (f,) = run(src, config=cfg)
    assert f.severity.value == "info"


def test_rule_subset_selection():
    src = """
        import jax
        import numpy as np
        @jax.jit
        def f(x):
            if x > 0:
                return np.asarray(x)
            return x
    """
    from paddle_tpu.analysis import get_rule
    only_002 = run(src, rules=[get_rule("TPL002")])
    assert {f.rule for f in only_002} == {"TPL002"}
