"""CI gate: the paddle_tpu tree must stay tpulint-clean.

Runs the real CLI (tools/tpulint.py) over paddle_tpu/ exactly as a
reviewer would, so the tier-1 pytest run doubles as the lint gate:
any new unsuppressed host-sync / retrace / RNG / lock / import-time
finding fails this test with the linter's own report as the message.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPULINT = os.path.join(REPO, "tools", "tpulint.py")


def _run(*args):
    return subprocess.run(
        [sys.executable, TPULINT, *args], cwd=REPO,
        capture_output=True, text=True, timeout=120)


def test_tree_is_tpulint_clean():
    proc = _run("paddle_tpu/", "--format", "json")
    doc = json.loads(proc.stdout)
    active = [f for f in doc["findings"] if not f.get("suppressed")]
    report = "\n".join(
        f"{f['path']}:{f['line']}: {f['rule']} {f['message']}"
        for f in active)
    assert proc.returncode == 0 and doc["clean"], (
        "tpulint found new TPU-hostile code — fix it or add a "
        "justified `# tpulint: disable=<RULE> -- why` suppression:\n"
        + report)
    # the gate must actually have looked at the tree
    assert doc["files_scanned"] > 150


def test_suppressions_carry_justifications():
    """Every inline suppression in the tree must give a reason (the
    `-- why` tail), so disables stay reviewable."""
    proc = _run("paddle_tpu/", "--format", "json")
    doc = json.loads(proc.stdout)
    bare = [f for f in doc["findings"]
            if f.get("suppressed") and not f.get("suppress_reason")]
    assert not bare, (
        "suppressions without a justification:\n" + "\n".join(
            f"{f['path']}:{f['line']}: {f['rule']}" for f in bare))


def test_cli_reports_findings_with_exit_1(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.numpy()\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    assert "TPL001" in proc.stdout


def test_cli_list_rules():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for rid in ("TPL001", "TPL002", "TPL003", "TPL004", "TPL005",
                "TPL006", "TPL007", "TPL008", "TPL009", "TPL010",
                "TPL011"):
        assert rid in proc.stdout


def test_env_docs_in_sync():
    """Satellite of the tpuracer pass: docs/env.md is generated from
    the paddle_tpu/_env.py knob registry; a knob added without
    regenerating the table fails here with a one-command fix."""
    gen = os.path.join(REPO, "tools", "gen_env_docs.py")
    proc = subprocess.run([sys.executable, gen, "--check"], cwd=REPO,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr


def test_env_docs_check_detects_drift(tmp_path):
    """--check must actually bite: a tampered docs/env.md fails."""
    gen = os.path.join(REPO, "tools", "gen_env_docs.py")
    doc = os.path.join(REPO, "docs", "env.md")
    with open(doc, "r", encoding="utf-8") as f:
        original = f.read()
    try:
        with open(doc, "a", encoding="utf-8") as f:
            f.write("\n| `PT_BOGUS_ROW` | `1` | int | tampered |\n")
        proc = subprocess.run([sys.executable, gen, "--check"],
                              cwd=REPO, capture_output=True, text=True,
                              timeout=60)
        assert proc.returncode == 1
        assert "out of sync" in proc.stderr
    finally:
        with open(doc, "w", encoding="utf-8") as f:
            f.write(original)


def test_pump_loop_single_sanctioned_device_get():
    """ISSUE 8: the engine's batched reader (`ServingEngine.
    _fetch_results`) must be the ONLY jax.device_get in the serving
    step loop — every other host pull rides it, so the pipelined pump
    has exactly one sync point to issue a step behind."""
    import ast

    readers = {}
    for rel in ("paddle_tpu/models/llama_serving.py",
                "paddle_tpu/serving/scheduler.py"):
        src = open(os.path.join(REPO, rel), encoding="utf-8").read()
        tree = ast.parse(src)

        def scan(node, stack):
            for child in ast.iter_child_nodes(node):
                nstack = stack
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    nstack = stack + [child.name]
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute) and \
                        child.func.attr == "device_get":
                    readers.setdefault(".".join(stack) or "<module>",
                                       0)
                    readers[".".join(stack) or "<module>"] += 1
                scan(child, nstack)
        scan(tree, [])
    assert set(readers) == {"ServingEngine._fetch_results"}, readers
    assert readers["ServingEngine._fetch_results"] == 1


def test_ragged_step_functions_in_hot_set():
    """ISSUE 11: the unified ragged step's builder/finish pair (and the
    shared `_bucket_for` bucket helper) are the per-wave hot loop now —
    they must sit in the default TPL001 hot set, and the single
    sanctioned sync must still be the engine's batched reader (the
    ragged paths fetch THROUGH it, never beside it)."""
    from paddle_tpu.analysis.config import LintConfig

    cfg = LintConfig.default()
    for fn in ("ServingEngine._ragged_launch",
               "ServingEngine._ragged_finish",
               "ServingEngine._bucket_for"):
        assert fn in cfg.hot_functions, fn
    assert cfg.sanctioned_sync == ["ServingEngine._fetch_results"]


def test_lean_epilogue_functions_in_hot_set():
    """ISSUE 12: the lean epilogue's lazy spec-row pull runs inside the
    acceptance loop — it belongs in the TPL001 hot set, and the single
    sanctioned sync is STILL the batched reader alone (the lazy pull is
    one more call through it, not beside it)."""
    from paddle_tpu.analysis.config import LintConfig

    cfg = LintConfig.default()
    assert "ServingEngine._spec_row_dist" in cfg.hot_functions
    assert cfg.sanctioned_sync == ["ServingEngine._fetch_results"]


def test_handoff_functions_in_hot_set():
    """ISSUE 13: the disaggregated handoff paths (harvest once per
    step, export/import moving KV pages through the kvtier copy
    thread's explicit fences) sit in the TPL001 hot set so a stray
    device pull can never hide in them — and the single sanctioned
    sync is STILL the batched reader alone (handoff copies are
    explicit-fence transfers on the tier thread, never a pump-thread
    device_get)."""
    from paddle_tpu.analysis.config import LintConfig

    cfg = LintConfig.default()
    for fn in ("ServingEngine._harvest_handoffs",
               "ServingEngine._export_handoff",
               "ServingEngine._import_handoff"):
        assert fn in cfg.hot_functions, fn
    assert cfg.sanctioned_sync == ["ServingEngine._fetch_results"]


def test_timeline_functions_in_hot_set():
    """ISSUE 14: the timeline/SLO plane is host-clock-only by contract
    — marks stamp on the pump and engine loops, finalize judges SLOs,
    the sentinel's note() runs once per step. All of it sits in the
    TPL001 hot set so a device pull can never sneak into the
    observability plane, and the single sanctioned sync is STILL the
    batched reader alone (the plane added zero device reads)."""
    from paddle_tpu.analysis.config import LintConfig

    cfg = LintConfig.default()
    for fn in ("Timeline.mark", "Timeline.count",
               "Timeline.segments", "Timeline.phases",
               "StepAnomalySentinel.note",
               "RequestScheduler._finalize",
               "RequestScheduler._account_slo",
               "RequestScheduler._timeline_entry"):
        assert fn in cfg.hot_functions, fn
    assert cfg.sanctioned_sync == ["ServingEngine._fetch_results"]
    # timeline.py lives in serving/ -> covered by the hot-module glob
    assert cfg.is_hot_module("paddle_tpu/serving/timeline.py")


def test_pulse_functions_in_hot_set():
    """ISSUE 15: the pulse plane's sampler and bundle writer run on
    the pulse/scrape threads against host-side registry snapshots —
    they sit in the TPL001 hot set (module AND function level) so a
    stray device pull can never hide in the observability plane, and
    the single sanctioned sync is STILL the batched reader alone (the
    plane added zero device reads)."""
    from paddle_tpu.analysis.config import LintConfig

    cfg = LintConfig.default()
    for fn in ("PulseSampler.sample",
               "PulsePlane.tick",
               "PulsePlane._check_triggers",
               "PulsePlane._write_bundle",
               "RequestScheduler._pulse_snapshot",
               "RequestScheduler._book_depth_locked"):
        assert fn in cfg.hot_functions, fn
    assert cfg.sanctioned_sync == ["ServingEngine._fetch_results"]
    assert cfg.is_hot_module("paddle_tpu/observability/pulse.py")


def test_fleet_functions_in_hot_set():
    """ISSUE 16: the fleet plane's bulk-channel threads (token stream
    serving, KV handoff shipping, page spill/fetch, the proxy's stream
    reader) are pure host+socket code riding the serving request path
    — they sit in the TPL001 hot set so a stray device pull can never
    hide in the transport, and the plane added zero sanctioned syncs."""
    from paddle_tpu.analysis.config import LintConfig

    cfg = LintConfig.default()
    for fn in ("FleetWorker._serve_stream",
               "FleetWorker._serve_handoff",
               "FleetPages._spill_loop",
               "FleetPages.fetch_missing",
               "RemoteRequest._read_loop"):
        assert fn in cfg.hot_functions, fn
    assert cfg.sanctioned_sync == ["ServingEngine._fetch_results"]
    assert cfg.is_hot_module("paddle_tpu/serving/fleet.py")
    assert cfg.is_hot_module("paddle_tpu/serving/wire.py")


def test_sanctioned_sync_config_check(tmp_path):
    """The TPL001 config check: a raw jax.device_get anywhere in a hot
    serving module — even outside the configured hot functions — is a
    finding; the sanctioned async result reader is clean."""
    hot_dir = tmp_path / "paddle_tpu" / "serving"
    hot_dir.mkdir(parents=True)
    bad = hot_dir / "rogue.py"
    bad.write_text(
        "import jax\n"
        "def helper(x):\n"
        "    return jax.device_get(x)\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    assert "TPL001" in proc.stdout
    assert "sanctioned" in proc.stdout
    good = hot_dir / "reader.py"
    good.write_text(
        "import jax\n"
        "class ServingEngine:\n"
        "    def _fetch_results(self, tree):\n"
        "        return jax.device_get(tree)\n")
    proc = _run(str(good))
    assert proc.returncode == 0, proc.stdout
