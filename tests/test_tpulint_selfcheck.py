"""CI gate: the paddle_tpu tree must stay tpulint-clean.

Runs the real CLI (tools/tpulint.py) over paddle_tpu/ exactly as a
reviewer would, so the tier-1 pytest run doubles as the lint gate:
any new unsuppressed host-sync / retrace / RNG / lock / import-time
finding fails this test with the linter's own report as the message.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPULINT = os.path.join(REPO, "tools", "tpulint.py")


def _run(*args):
    return subprocess.run(
        [sys.executable, TPULINT, *args], cwd=REPO,
        capture_output=True, text=True, timeout=120)


def test_tree_is_tpulint_clean():
    proc = _run("paddle_tpu/", "--format", "json")
    doc = json.loads(proc.stdout)
    active = [f for f in doc["findings"] if not f.get("suppressed")]
    report = "\n".join(
        f"{f['path']}:{f['line']}: {f['rule']} {f['message']}"
        for f in active)
    assert proc.returncode == 0 and doc["clean"], (
        "tpulint found new TPU-hostile code — fix it or add a "
        "justified `# tpulint: disable=<RULE> -- why` suppression:\n"
        + report)
    # the gate must actually have looked at the tree
    assert doc["files_scanned"] > 150


def test_suppressions_carry_justifications():
    """Every inline suppression in the tree must give a reason (the
    `-- why` tail), so disables stay reviewable."""
    proc = _run("paddle_tpu/", "--format", "json")
    doc = json.loads(proc.stdout)
    bare = [f for f in doc["findings"]
            if f.get("suppressed") and not f.get("suppress_reason")]
    assert not bare, (
        "suppressions without a justification:\n" + "\n".join(
            f"{f['path']}:{f['line']}: {f['rule']}" for f in bare))


def test_cli_reports_findings_with_exit_1(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.numpy()\n")
    proc = _run(str(bad))
    assert proc.returncode == 1
    assert "TPL001" in proc.stdout


def test_cli_list_rules():
    proc = _run("--list-rules")
    assert proc.returncode == 0
    for rid in ("TPL001", "TPL002", "TPL003", "TPL004", "TPL005",
                "TPL006"):
        assert rid in proc.stdout
