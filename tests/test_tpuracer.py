"""tpuracer tests: the cross-file project index (thread entries, lock
inventory, acquisition-order graph, attribute ownership) and the rules
riding it — TPL007 lock-order inversion, TPL008 unlocked shared
writes, TPL009 blocking-under-lock, TPL010 env-registry drift, TPL011
metrics-contract drift — plus the CLI surfaces (--threads, --changed,
hard TPL000 findings for rotten inputs) and the `paddle_tpu._env`
accessor semantics the registry contract rests on."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from paddle_tpu import _env
from paddle_tpu.analysis import LintConfig, lint_source
from paddle_tpu.analysis.context import FileContext
from paddle_tpu.analysis.project import (CALLER_ENTRY, ProjectIndex,
                                         pretty_key)
from paddle_tpu.analysis.runner import analyze_paths, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPULINT = os.path.join(REPO, "tools", "tpulint.py")

# any path with this suffix lands in the default concurrency_scope /
# env_migrated / lock_scope globs
SCOPED = "paddle_tpu/serving/fixture.py"


def run(src, path=SCOPED, config=None):
    return lint_source(textwrap.dedent(src), path=path,
                       config=config or LintConfig.default())


def rule_ids(src, **kw):
    return sorted({f.rule for f in run(src, **kw) if not f.suppressed})


def build_index(files, config=None):
    """ProjectIndex over {path: source} without the rule layer."""
    config = config or LintConfig.default()
    ctxs = [FileContext(p, textwrap.dedent(s), config)
            for p, s in sorted(files.items())]
    return ProjectIndex.build(ctxs, config)


def write_tree(tmp_path, files):
    """Materialize {relpath: source} under tmp_path; returns the root
    as a string for lint_paths/CLI runs."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _cli(*args, cwd=REPO):
    return subprocess.run([sys.executable, TPULINT, *args], cwd=cwd,
                          capture_output=True, text=True, timeout=120)


# ===================================================== TPL007 lock order
INVERTED = """
    import threading

    class Pair:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def backward(self):
            with self._b:
                with self._a:
                    pass
"""


class TestLockOrder:
    def test_fires_on_inverted_nesting(self):
        out = [f for f in run(INVERTED) if f.rule == "TPL007"]
        assert len(out) == 1                 # one finding per cycle
        assert "lock-order inversion" in out[0].message
        assert "Pair._a" in out[0].message and "Pair._b" in out[0].message

    def test_silent_on_consistent_order(self):
        assert "TPL007" not in rule_ids("""
            import threading

            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def also_forward(self):
                    with self._a:
                        with self._b:
                            pass
        """)

    def test_fires_across_classes_via_calls(self):
        """The inversion hides behind a call edge: Left holds its lock
        and calls into Right, which holds its own and calls back."""
        assert "TPL007" in rule_ids("""
            import threading

            class Right:
                def __init__(self):
                    self._rlock = threading.Lock()
                    self.left = Left()

                def poke(self):
                    with self._rlock:
                        self.left.nudge()

            class Left:
                def __init__(self):
                    self._llock = threading.Lock()
                    self.right = Right()

                def nudge(self):
                    with self._llock:
                        self.right.poke()
        """)

    def test_unit_cycle_witness(self):
        idx = build_index({SCOPED: INVERTED})
        cycles = idx.lock_cycles()
        assert len(cycles) == 1
        ids, witness = cycles[0]
        assert ids == ["Pair._a", "Pair._b"]
        assert witness.path == SCOPED

    def test_unit_transitive_edge_through_call(self):
        idx = build_index({SCOPED: """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def outer(self):
                    with self._a:
                        self._inner()

                def _inner(self):
                    with self._b:
                        pass
        """})
        edges = {(e.src, e.dst) for e in idx.lock_order_edges()}
        assert ("C._a", "C._b") in edges
        assert not idx.lock_cycles()


# ================================================ TPL008 shared writes
RACY = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def start(self):
            threading.Thread(target=self._pump, name="pt-pump").start()
            threading.Thread(target=self._drain).start()

        def _pump(self):
            self.count = self.count + 1

        def _drain(self):
            self.count = 0
"""


class TestSharedWrites:
    def test_fires_on_two_thread_writers_no_lock(self):
        out = [f for f in run(RACY) if f.rule == "TPL008"]
        assert len(out) == 1
        assert "self.count" in out[0].message
        assert "Worker._pump" in out[0].message
        assert "Worker._drain" in out[0].message

    def test_silent_with_common_lock(self):
        assert "TPL008" not in rule_ids("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0

                def start(self):
                    threading.Thread(target=self._pump).start()
                    threading.Thread(target=self._drain).start()

                def _pump(self):
                    with self._lock:
                        self.count = self.count + 1

                def _drain(self):
                    with self._lock:
                        self.count = 0
        """)

    def test_silent_single_writer_delta_mirror(self):
        """One owning thread writes; everyone else only reads — the
        delta-mirror pattern must not fire."""
        assert "TPL008" not in rule_ids("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0

                def start(self):
                    threading.Thread(target=self._pump).start()

                def _pump(self):
                    self.count = self.count + 1

                def peek(self):
                    return self.count
        """)

    def test_locked_suffix_counts_as_holding_class_locks(self):
        """`*_locked` methods document "caller holds the lock"; writes
        inside them share the class lock with `with`-guarded writers."""
        assert "TPL008" not in rule_ids("""
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0

                def start(self):
                    threading.Thread(target=self._pump).start()
                    threading.Thread(target=self._drain).start()

                def _pump(self):
                    with self._lock:
                        self._bump_locked()

                def _bump_locked(self):
                    self.count = self.count + 1

                def _drain(self):
                    with self._lock:
                        self.count = 0
        """)

    def test_unit_entry_points_and_ownership(self):
        idx = build_index({SCOPED: RACY})
        entries = dict(idx.entry_points())
        assert "Worker._pump" in entries
        assert "Worker._drain" in entries
        assert CALLER_ENTRY in entries        # public API pseudo-entry
        owners = idx.ownership_map()
        # __init__ writes are construction, not contention
        assert ("Worker", "count") in owners
        writers = owners[("Worker", "count")]
        assert set(writers) == {"Worker._pump", "Worker._drain"}

    def test_unit_thread_report_carries_name_hint(self):
        idx = build_index({SCOPED: RACY})
        rows = idx.thread_report()
        assert ("pt-pump", "Worker._pump", f"{SCOPED}:10") in rows


# ============================================ TPL009 blocking under lock
class TestBlockingUnderLock:
    def test_fires_on_sendall_under_lock(self):
        out = [f for f in run("""
            import socket
            import threading

            class Client:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = socket.create_connection(("h", 1))

                def send(self, data):
                    with self._lock:
                        self._sock.sendall(data)
        """) if f.rule == "TPL009"]
        assert len(out) == 1
        assert "sendall" in out[0].message
        assert "Client._lock" in out[0].message

    def test_silent_when_lock_is_an_io_mutex(self):
        """*_wlock names declare "this lock serializes one socket" —
        spanning its own sends is the point."""
        assert "TPL009" not in rule_ids("""
            import socket
            import threading

            class Client:
                def __init__(self):
                    self._wlock = threading.Lock()
                    self._sock = socket.create_connection(("h", 1))

                def send(self, data):
                    with self._wlock:
                        self._sock.sendall(data)
        """)

    def test_silent_outside_lock(self):
        assert "TPL009" not in rule_ids("""
            import socket
            import threading

            class Client:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = socket.create_connection(("h", 1))

                def send(self, data):
                    with self._lock:
                        payload = bytes(data)
                    self._sock.sendall(payload)
        """)

    def test_fires_on_queue_get_without_timeout(self):
        out = [f for f in run("""
            import queue
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        item = self._q.get()
                    return item
        """) if f.rule == "TPL009"]
        assert len(out) == 1
        assert "queue get, no timeout" in out[0].message

    def test_silent_on_queue_get_with_timeout(self):
        assert "TPL009" not in rule_ids("""
            import queue
            import threading

            class Pump:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()

                def drain(self):
                    with self._lock:
                        item = self._q.get(timeout=0.5)
                    return item
        """)

    def test_fires_transitively_across_files(self, tmp_path):
        """node.py holds a lock and calls wire.send_msg, which lives in
        another file and blocks on the socket — the finding lands at
        the call site in node.py and names the hop."""
        root = write_tree(tmp_path, {
            "paddle_tpu/serving/wire.py": """
                def send_msg(sock, payload):
                    sock.sendall(payload)
            """,
            "paddle_tpu/serving/node.py": """
                import threading

                from .wire import send_msg

                class Node:
                    def __init__(self, sock):
                        self._lock = threading.Lock()
                        self.sock = sock

                    def publish(self, payload):
                        with self._lock:
                            send_msg(self.sock, payload)
            """,
        })
        findings, _ = lint_paths([root])
        hits = [f for f in findings if f.rule == "TPL009"]
        assert len(hits) == 1
        assert hits[0].path.endswith("node.py")
        assert "wire.send_msg" in hits[0].message


# ================================================ TPL010 env registry
class TestEnvRegistry:
    def test_fires_on_undeclared_knob(self):
        out = [f for f in run("""
            import os

            def flag():
                return os.environ.get("PT_UNDECLARED_KNOB", "0")
        """) if f.rule == "TPL010"]
        assert len(out) == 1
        assert "PT_UNDECLARED_KNOB" in out[0].message
        assert "not declared" in out[0].message

    def test_fires_on_subscript_and_membership_reads(self):
        out = [f for f in run("""
            import os

            def pair():
                a = os.environ["PT_SUB_KNOB"]
                b = "PT_IN_KNOB" in os.environ
                return a, b
        """) if f.rule == "TPL010"]
        assert {m for f in out for m in ("PT_SUB_KNOB", "PT_IN_KNOB")
                if m in f.message} == {"PT_SUB_KNOB", "PT_IN_KNOB"}

    def test_silent_on_foreign_namespaces(self):
        assert "TPL010" not in rule_ids("""
            import os

            def home():
                return os.environ.get("HOME", "/")
        """)

    def test_declared_knob_raw_read_in_migrated_package(self, tmp_path):
        """A declared knob read via raw os.environ inside a migrated
        package fires; the accessor read is clean; a pattern-family
        member counts as declared."""
        root = write_tree(tmp_path, {
            "paddle_tpu/_env.py": """
                def declare(name, default, doc, *, kind="str",
                            section="general"):
                    return name

                declare("PT_FIXTURE_DEPTH", 8, "test knob", kind="int")
                declare("PT_FIXTURE_*_S", None, "family", kind="float")
            """,
            "paddle_tpu/serving/reader.py": """
                import os

                from .._env import env_float, env_int

                def raw():
                    return os.environ.get("PT_FIXTURE_DEPTH", "8")

                def clean():
                    return (env_int("PT_FIXTURE_DEPTH"),
                            env_float("PT_FIXTURE_WAIT_S", 1.0))
            """,
        })
        findings, _ = lint_paths([root])
        hits = [f for f in findings if f.rule == "TPL010"]
        assert len(hits) == 1
        assert "raw os.environ read of declared knob" in hits[0].message
        assert hits[0].path.endswith("reader.py")


# ============================================ TPL011 metrics contract
def _metrics_config(tmp_path, doc_text):
    doc = tmp_path / "metrics.md"
    doc.write_text(textwrap.dedent(doc_text))
    cfg = LintConfig.default()
    cfg.metrics_docs = [str(doc)]
    return cfg


class TestMetricsContract:
    def test_fires_on_undocumented_booking(self, tmp_path):
        cfg = _metrics_config(tmp_path, """
            | Metric | Meaning |
            |---|---|
            | `pt_documented_total` | counted |
        """)
        out = [f for f in run("""
            def book(r):
                return r.counter("pt_rogue_metric", "no docs row")
        """, path="paddle_tpu/serving/m.py", config=cfg)
            if f.rule == "TPL011"]
        assert len(out) == 1
        assert "pt_rogue_metric" in out[0].message

    def test_total_suffix_tolerance(self, tmp_path):
        """Counters render `<name>_total` in the exposition; docs rows
        using either form match the booking."""
        cfg = _metrics_config(tmp_path, """
            | Metric | Meaning |
            |---|---|
            | `pt_reqs_total` | requests |
        """)
        assert "TPL011" not in rule_ids("""
            def book(r):
                return r.counter("pt_reqs", "requests")
        """, path="paddle_tpu/serving/m.py", config=cfg)

    def test_brace_rows_expand(self, tmp_path):
        cfg = _metrics_config(tmp_path, """
            | Metric | Meaning |
            |---|---|
            | `pt_cache_{hits,misses}_total` | cache outcome |
        """)
        assert "TPL011" not in rule_ids("""
            def book(r):
                a = r.counter("pt_cache_hits", "x")
                b = r.counter("pt_cache_misses", "y")
                return a, b
        """, path="paddle_tpu/serving/m.py", config=cfg)

    def test_ghost_documented_metric_fires_at_registry(self, tmp_path):
        cfg = _metrics_config(tmp_path, """
            | Metric | Meaning |
            |---|---|
            | `pt_ghost_metric` | long gone |
            | `pt_live_metric` | still booked |
        """)
        out = [f for f in run("""
            class MetricsRegistry:
                def counter(self, name, doc):
                    return name

            def book(r):
                return r.counter("pt_live_metric", "alive")
        """, path="paddle_tpu/serving/m.py", config=cfg)
            if f.rule == "TPL011"]
        assert len(out) == 1
        assert "pt_ghost_metric" in out[0].message
        assert "never booked" in out[0].message

    def test_fstring_booking_matches_documented_member(self, tmp_path):
        """f-string bookings (pt_phase_{ph}_seconds) are recorded as
        patterns, so documented concrete members are not ghosts."""
        cfg = _metrics_config(tmp_path, """
            | Metric | Meaning |
            |---|---|
            | `pt_phase_prefill_seconds` | phase split |
        """)
        assert "TPL011" not in rule_ids("""
            class MetricsRegistry:
                def histogram(self, name, doc):
                    return name

            def book(r, ph):
                return r.histogram(f"pt_phase_{ph}_seconds", "split")
        """, path="paddle_tpu/serving/m.py", config=cfg)

    def test_silent_when_no_docs_exist(self, tmp_path):
        cfg = LintConfig.default()
        cfg.metrics_docs = [str(tmp_path / "nope-*.md")]
        assert "TPL011" not in rule_ids("""
            def book(r):
                return r.counter("pt_whatever", "x")
        """, path="paddle_tpu/serving/m.py", config=cfg)


# ================================================= suppression grammar
class TestSuppressions:
    def test_disable_next_line_with_reason(self):
        out = run("""
            import socket
            import threading

            class Client:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._sock = socket.create_connection(("h", 1))

                def send(self, data):
                    with self._lock:
                        # tpulint: disable-next-line=TPL009 -- drill
                        self._sock.sendall(data)
        """)
        hits = [f for f in out if f.rule == "TPL009"]
        assert len(hits) == 1
        assert hits[0].suppressed
        assert hits[0].suppress_reason == "drill"

    def test_trailing_disable_on_witness_line(self):
        src = RACY.replace(
            "self.count = self.count + 1",
            "self.count = self.count + 1  "
            "# tpulint: disable=TPL008 -- fixture")
        hits = [f for f in run(src) if f.rule == "TPL008"]
        assert len(hits) == 1 and hits[0].suppressed


# ======================================================== project index
class TestProjectIndex:
    def test_pretty_key(self):
        assert pretty_key("Worker._pump") == "Worker._pump"
        assert pretty_key("a/b/wire.py::send_msg") == "wire.send_msg"

    def test_env_pattern_declarations(self):
        idx = build_index({"paddle_tpu/_env.py": """
            def declare(name, default, doc, **kw):
                return name

            declare("PT_EXACT", 1, "x")
            declare("PT_FAM_*_S", None, "family")
        """})
        assert idx.env_is_declared("PT_EXACT")
        assert idx.env_is_declared("PT_FAM_DECODE_S")
        assert not idx.env_is_declared("PT_OTHER")
        assert idx.has_env_registry

    def test_reachability_is_transitive(self):
        idx = build_index({SCOPED: """
            class C:
                def a(self):
                    self.b()

                def b(self):
                    self.c()

                def c(self):
                    pass
        """})
        assert idx.reachable(["C.a"]) == {"C.a", "C.b", "C.c"}

    def test_index_is_conservative_on_unresolvable_calls(self):
        """Unknown call targets contribute nothing — no guessed
        findings, no phantom graph nodes."""
        idx = build_index({SCOPED: """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def go(self, helper):
                    with self._lock:
                        helper.mystery()
        """})
        assert not idx.lock_cycles()
        assert not idx.blocking_under_lock()


# ===================================================== CLI hard findings
class TestCLIHardFindings:
    def test_nonexistent_path_is_a_finding_not_a_skip(self, tmp_path):
        proc = _cli(str(tmp_path / "gone.py"))
        assert proc.returncode == 1
        assert "TPL000" in proc.stdout
        assert "does not exist" in proc.stdout

    def test_unreadable_file_is_a_finding(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_bytes(b"\xff\xfe\xff not utf-8 \xff")
        proc = _cli(str(bad), "--format", "json")
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert [f["rule"] for f in doc["findings"]] == ["TPL000"]
        assert "cannot read" in doc["findings"][0]["message"]

    def test_syntax_error_is_a_finding_with_location(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n    pass\n")
        proc = _cli(str(bad), "--format", "json")
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["findings"][0]["rule"] == "TPL000"
        assert "syntax error" in doc["findings"][0]["message"]


# ========================================================= CLI --threads
class TestCLIThreads:
    def test_threads_inventory(self, tmp_path):
        root = write_tree(tmp_path, {
            "paddle_tpu/serving/w.py": RACY,
        })
        proc = _cli(root, "--threads")
        assert proc.returncode == 0
        assert "Worker._pump" in proc.stdout
        assert "pt-pump" in proc.stdout
        assert "<caller>" in proc.stdout


# ========================================================= CLI --changed
BAD_SYNC = """
import jax

@jax.jit
def f(x):
    return x.numpy()
"""


class TestCLIChanged:
    def _git(self, repo, *args):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=repo, check=True, capture_output=True, timeout=30)

    def test_changed_filters_to_touched_files(self, tmp_path):
        (tmp_path / "old.py").write_text(BAD_SYNC)
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "old.py")
        self._git(tmp_path, "commit", "-qm", "seed")
        (tmp_path / "new.py").write_text(BAD_SYNC.replace("f(", "g("))

        full = _cli(".", cwd=tmp_path)
        assert full.returncode == 1
        assert "old.py" in full.stdout and "new.py" in full.stdout

        changed = _cli(".", "--changed", "HEAD", cwd=tmp_path)
        assert changed.returncode == 1
        assert "new.py" in changed.stdout
        assert "old.py" not in changed.stdout

    def test_changed_clean_when_touched_files_clean(self, tmp_path):
        (tmp_path / "old.py").write_text(BAD_SYNC)
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "old.py")
        self._git(tmp_path, "commit", "-qm", "seed")
        (tmp_path / "new.py").write_text("x = 1\n")
        proc = _cli(".", "--changed", "HEAD", cwd=tmp_path)
        assert proc.returncode == 0

    def test_bad_ref_is_a_usage_error(self, tmp_path):
        (tmp_path / "a.py").write_text("x = 1\n")
        self._git(tmp_path, "init", "-q")
        proc = _cli(".", "--changed", "no-such-ref", cwd=tmp_path)
        assert proc.returncode == 2


# ===================================================== _env accessors
class TestEnvAccessors:
    def test_declared_defaults_flow_through(self):
        assert _env.env_int("PT_PULSE_DEPTH", env={}) == 240
        assert _env.env_int("PT_PULSE_DEPTH", env={"PT_PULSE_DEPTH": "8"}) == 8

    def test_empty_string_falls_back_for_numbers(self):
        assert _env.env_int("PT_PULSE_DEPTH",
                            env={"PT_PULSE_DEPTH": " "}) == 240

    def test_bool_semantics(self):
        assert _env.env_bool("PT_SERVE_PIPELINE", env={}) is False
        assert _env.env_bool("PT_SERVE_PIPELINE",
                             env={"PT_SERVE_PIPELINE": "1"}) is True
        assert _env.env_bool("PT_SERVE_PIPELINE",
                             env={"PT_SERVE_PIPELINE": "0"}) is False
        assert _env.env_bool("PT_SERVE_PIPELINE",
                             env={"PT_SERVE_PIPELINE": ""}) is False

    def test_undeclared_name_raises(self):
        with pytest.raises(KeyError):
            _env.env_str("PT_NOT_A_KNOB", env={})

    def test_pattern_family_requires_call_site_default(self):
        fam = [k for k in _env.knobs() if k.is_pattern]
        assert fam, "expected at least one pattern family knob"
        member = fam[0].name.replace("*", "X")
        assert _env.is_declared(member)
        with pytest.raises(KeyError):
            _env.env_str(member, env={})
        assert _env.env_str(member, "fallback", env={}) == "fallback"


# ============================================== two-phase runner seams
class TestAnalyzePaths:
    def test_rule_subset_still_builds_full_index(self, tmp_path):
        root = write_tree(tmp_path, {"paddle_tpu/serving/w.py": RACY})
        findings, nfiles, project = analyze_paths([root])
        assert nfiles == 1
        assert any(f.rule == "TPL008" for f in findings)
        assert project.thread_entries
