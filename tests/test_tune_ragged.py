"""tools/tune_ragged.py smoke lane (ISSUE 12): the offline ragged-tile
autotuner's sweep/verify/persist/reload loop must be proven on CPU
before it runs unattended in a TPU tunnel window, and a persisted tile
must actually reach a constructed ServingEngine — as a STATIC kernel
arg, with token-identical outputs and zero serving-time retraces.
"""
import json
import os
import subprocess
import sys

import jax.numpy as jnp
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TUNER = os.path.join(ROOT, "tools", "tune_ragged.py")

from paddle_tpu import _tuning_defaults as TD
from paddle_tpu.models.llama import LlamaConfig
from paddle_tpu.models import llama_spmd as M
from paddle_tpu.models.llama_serving import Request, ServingEngine

CFG = LlamaConfig.tiny(vocab=64, hidden=32, layers=2, heads=4, kv_heads=2,
                       ffn=64, seq=128)


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, seed=0, dtype=jnp.float32)


def test_smoke_sweep_verifies_persists_reloads(tmp_path):
    out = str(tmp_path / "TUNED.kernels.smoke.json")
    r = subprocess.run(
        [sys.executable, TUNER, "--smoke", "--out", out, "--iters", "1"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr + r.stdout
    with open(out) as f:
        data = json.load(f)
    entry = data["ragged"]["cpu"]
    assert {"block_q", "block_pages", "smoke", "trials"} <= set(entry)
    assert entry["smoke"] is True
    # every surviving trial was BIT-verified against the seed tile
    assert all(t["exact"] for t in entry["trials"]
               if t["time_s"] is not None)
    assert len(entry["trials"]) >= 3
    # the tool's machine-readable summary line is the tunnel contract
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["generation"] == "cpu"
    assert summary["best"] == {"block_q": entry["block_q"],
                               "block_pages": entry["block_pages"]}
    # what was persisted is what the engine-side loader resolves
    assert TD.load_ragged_tile("cpu", path=out) == \
        (entry["block_q"], entry["block_pages"])


def test_tuner_refuses_real_run_without_tpu(tmp_path):
    out = str(tmp_path / "TUNED.kernels.json")
    r = subprocess.run(
        [sys.executable, TUNER, "--out", out],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "TPU unreachable" in r.stderr
    assert not os.path.exists(out)


def test_engine_picks_up_persisted_tile(tmp_path, monkeypatch, params):
    """A tuned tile file -> ServingEngine statics, and the tuned engine
    is token-identical to the default-tile one (the sweep's bit-verify
    contract, re-proven through the whole serving stack)."""
    path = str(tmp_path / "tiles.json")
    TD.save_ragged_tile("cpu", 16, 2, path=path)
    monkeypatch.setattr(TD, "RAGGED_TILE_FILE", path)

    def run(tuned):
        if not tuned:
            monkeypatch.setattr(TD, "RAGGED_TILE_FILE",
                                str(tmp_path / "absent.json"))
        else:
            monkeypatch.setattr(TD, "RAGGED_TILE_FILE", path)
        eng = ServingEngine(params, CFG, max_seqs=2, max_seq_len=64,
                            page_size=8, use_pallas=False, ragged=True)
        if tuned:
            assert (eng._block_q, eng._block_pages) == (16, 2)
        else:   # untuned chip: builtin seed defaults
            assert (eng._block_q, eng._block_pages) == (None, 1)
        eng.submit(Request("g", [1, 5, 9, 3], max_new_tokens=8))
        eng.submit(Request("s", [2, 4, 6], max_new_tokens=8,
                           temperature=0.8, top_k=8, seed=7))
        return {r.rid: r.output for r in eng.run()}

    assert run(tuned=False) == run(tuned=True)


def test_env_override_beats_tile_file(tmp_path, monkeypatch):
    path = str(tmp_path / "tiles.json")
    TD.save_ragged_tile("cpu", 16, 2, path=path)
    monkeypatch.setenv("PT_RAGGED_BLOCK_Q", "24")
    assert TD.load_ragged_tile("cpu", path=path) == (24, 2)
