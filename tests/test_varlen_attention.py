"""Varlen (unpadded) flash attention vs per-segment dense reference.

Reference parity target: python/paddle/nn/functional/flash_attention.py:756
(flash_attn_unpadded with cu_seqlens prefix sums)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.ops.varlen_attention import (flash_attn_unpadded,
                                             flash_attention_varlen,
                                             varlen_reference, rev_pos,
                                             seg_ids_from_cu_seqlens)

H, D = 4, 32


def dense_ref(q, k, v, cuq, cuk, causal):
    """Per-segment dense attention; causal is bottom-right aligned
    (flash-attention semantics for unequal q/k lengths)."""
    outs = []
    for i in range(len(cuq) - 1):
        a, b = cuq[i], cuq[i + 1]
        c, d = cuk[i], cuk[i + 1]
        qi, ki, vi = q[a:b], k[c:d], v[c:d]
        lq, lk = b - a, d - c
        s = np.einsum("qhd,khd->hqk", qi, ki) / np.sqrt(D)
        if causal:
            m = np.arange(lk)[None, :] <= np.arange(lq)[:, None] + (lk - lq)
            s = np.where(m[None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        outs.append(np.einsum("hqk,khd->qhd", p, vi))
    return np.concatenate(outs, 0)


def _cu(lens):
    return np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)


class TestVarlenForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_per_segment_dense(self, causal):
        rng = np.random.RandomState(0)
        cu = _cu([37, 128, 3, 60])
        t = int(cu[-1])
        q = rng.randn(t, H, D).astype(np.float32)
        k = rng.randn(t, H, D).astype(np.float32)
        v = rng.randn(t, H, D).astype(np.float32)
        out, _ = flash_attn_unpadded(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), cu, cu, causal=causal,
                                     use_pallas=True, interpret=True)
        ref = dense_ref(q, k, v, cu, cu, causal)
        assert np.max(np.abs(np.asarray(out) - ref)) < 2e-4

    @pytest.mark.parametrize("causal", [False, True])
    def test_unequal_qk_lengths(self, causal):
        """kv-cache/cross-attn case: separate cu_seqlens_q / cu_seqlens_k,
        causal bottom-right aligned per segment."""
        rng = np.random.RandomState(1)
        cuq, cuk = _cu([2, 3, 5]), _cu([4, 3, 9])
        q = rng.randn(int(cuq[-1]), H, D).astype(np.float32)
        k = rng.randn(int(cuk[-1]), H, D).astype(np.float32)
        v = rng.randn(int(cuk[-1]), H, D).astype(np.float32)
        out, _ = flash_attn_unpadded(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), cuq, cuk, causal=causal,
                                     use_pallas=True, interpret=True)
        ref = dense_ref(q, k, v, cuq, cuk, causal)
        assert np.max(np.abs(np.asarray(out) - ref)) < 2e-4

    def test_gqa_heads(self):
        rng = np.random.RandomState(2)
        cu = _cu([10, 22])
        t = int(cu[-1])
        q = rng.randn(t, 8, D).astype(np.float32)
        k = rng.randn(t, 2, D).astype(np.float32)
        v = rng.randn(t, 2, D).astype(np.float32)
        out, _ = flash_attn_unpadded(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), cu, cu, causal=True,
                                     use_pallas=True, interpret=True)
        kr = np.repeat(k, 4, axis=1)
        vr = np.repeat(v, 4, axis=1)
        ref = dense_ref(q, kr, vr, cu, cu, True)
        assert np.max(np.abs(np.asarray(out) - ref)) < 2e-4

    def test_first_token_attends_only_itself(self):
        rng = np.random.RandomState(3)
        cu = _cu([5, 12, 3])
        t = int(cu[-1])
        q = jnp.asarray(rng.randn(t, H, D), jnp.float32)
        out, _ = flash_attn_unpadded(q, q, q, cu, cu, causal=True,
                                     use_pallas=True, interpret=True)
        for s in cu[:-1]:
            assert np.allclose(np.asarray(out[s]), np.asarray(q[s]),
                               atol=1e-5)


class TestVarlenBackward:
    def test_grads_match_reference(self):
        rng = np.random.RandomState(4)
        cu = _cu([37, 100, 19])
        t = int(cu[-1])
        q = jnp.asarray(rng.randn(t, H, D), jnp.float32)
        k = jnp.asarray(rng.randn(t, H, D), jnp.float32)
        v = jnp.asarray(rng.randn(t, H, D), jnp.float32)
        segs = seg_ids_from_cu_seqlens(jnp.asarray(cu), t)

        def f_pallas(q, k, v):
            return jnp.sum(flash_attention_varlen(
                q, k, v, segs, segs, causal=True, use_pallas=True,
                interpret=True) ** 2)

        def f_ref(q, k, v):
            o, _ = varlen_reference(jnp.swapaxes(q, 0, 1),
                                    jnp.swapaxes(k, 0, 1),
                                    jnp.swapaxes(v, 0, 1), segs, segs, True,
                                    1.0 / np.sqrt(D))
            return jnp.sum(jnp.swapaxes(o, 0, 1) ** 2)

        g = jax.grad(f_pallas, (0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            assert float(jnp.max(jnp.abs(a - b))) < 2e-3

    def test_grads_unequal_lengths(self):
        rng = np.random.RandomState(5)
        cuq, cuk = _cu([2, 7]), _cu([6, 9])
        sq = seg_ids_from_cu_seqlens(jnp.asarray(cuq), int(cuq[-1]))
        sk = seg_ids_from_cu_seqlens(jnp.asarray(cuk), int(cuk[-1]))
        q = jnp.asarray(rng.randn(int(cuq[-1]), H, D), jnp.float32)
        k = jnp.asarray(rng.randn(int(cuk[-1]), H, D), jnp.float32)
        v = jnp.asarray(rng.randn(int(cuk[-1]), H, D), jnp.float32)

        def f_pallas(q, k, v):
            return jnp.sum(flash_attention_varlen(
                q, k, v, sq, sk, causal=True, use_pallas=True,
                interpret=True) ** 2)

        def f_ref(q, k, v):
            o, _ = varlen_reference(jnp.swapaxes(q, 0, 1),
                                    jnp.swapaxes(k, 0, 1),
                                    jnp.swapaxes(v, 0, 1), sq, sk, True,
                                    1.0 / np.sqrt(D))
            return jnp.sum(jnp.swapaxes(o, 0, 1) ** 2)

        g = jax.grad(f_pallas, (0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, (0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            assert float(jnp.max(jnp.abs(a - b))) < 2e-3


class TestVarlenSurface:
    def test_nn_functional_parity_entry(self):
        rng = np.random.RandomState(6)
        cu = _cu([8, 16])
        t = int(cu[-1])
        q = pt.to_tensor(rng.randn(t, H, D).astype(np.float32))
        out, sm = pt.nn.functional.flash_attn_unpadded(q, q, q, cu, cu,
                                                       causal=True)
        assert sm is None
        assert np.isfinite(out.numpy()).all()
        assert out.shape == [t, H, D]

    def test_dropout_on_probabilities(self):
        """dropout>0 must change results (applied to P, on the XLA path)
        and keep rows normalized in expectation — not zero whole outputs."""
        pt.seed(0)
        rng = np.random.RandomState(7)
        cu = _cu([64])
        t = int(cu[-1])
        q = pt.to_tensor(rng.randn(t, 2, 16).astype(np.float32))
        o0, _ = pt.nn.functional.flash_attn_unpadded(q, q, q, cu, cu)
        o1, _ = pt.nn.functional.flash_attn_unpadded(q, q, q, cu, cu,
                                                     dropout=0.5)
        assert not np.allclose(o0.numpy(), o1.numpy())
        # E[dropped P] = P, so the mean over many keys stays in range
        assert np.isfinite(o1.numpy()).all()

    def test_rev_pos(self):
        seg = jnp.asarray([0, 0, 0, 1, 1, 2], jnp.int32)
        r = np.asarray(rev_pos(seg))
        assert list(r) == [3, 2, 1, 2, 1, 1]


class TestVarlenPadding:
    def test_pad_rows_produce_zero_not_garbage(self):
        """Tokens past cu_seqlens[-1] must attend nothing: pad q rows give
        exactly 0 output (safe-l), and real rows are unaffected by pads."""
        rng = np.random.RandomState(8)
        cu = _cu([5, 9])
        t = int(cu[-1])
        pad = 6
        q = rng.randn(t + pad, H, D).astype(np.float32)
        out, _ = flash_attn_unpadded(jnp.asarray(q), jnp.asarray(q),
                                     jnp.asarray(q), cu, cu, causal=True,
                                     use_pallas=True, interpret=True)
        assert np.allclose(np.asarray(out[t:]), 0.0), "pad rows not zero"
        out_nopad, _ = flash_attn_unpadded(jnp.asarray(q[:t]),
                                           jnp.asarray(q[:t]),
                                           jnp.asarray(q[:t]), cu, cu,
                                           causal=True, use_pallas=True,
                                           interpret=True)
        assert np.abs(np.asarray(out[:t]) - np.asarray(out_nopad)).max() < 1e-5

    def test_padded_one_side_causal_still_correct(self):
        """k-side padded beyond cu while q exact: rev_pos sanitization must
        keep real segment ends correct (non-monotone seg would corrupt)."""
        rng = np.random.RandomState(9)
        cu = _cu([4, 6])
        t = int(cu[-1])
        q = rng.randn(t, H, D).astype(np.float32)
        k = rng.randn(t + 6, H, D).astype(np.float32)
        k[:t] = rng.randn(t, H, D)
        v = rng.randn(t + 6, H, D).astype(np.float32)
        out, _ = flash_attn_unpadded(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), cu, cu, causal=True,
                                     use_pallas=True, interpret=True)
        ref = dense_ref(q, k[:t], v[:t], cu, cu, True)
        assert np.abs(np.asarray(out) - ref).max() < 2e-4
