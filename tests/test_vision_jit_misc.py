"""Vision models / jit / distribution / sparse / incubate tests."""
import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as pt


class TestVisionModels:
    def test_lenet_shapes(self):
        net = pt.vision.models.LeNet()
        assert net(pt.randn([2, 1, 28, 28])).shape == [2, 10]

    def test_resnet18_forward_backward(self):
        net = pt.vision.models.resnet18(num_classes=10)
        net.eval()
        out = net(pt.randn([1, 3, 64, 64]))
        assert out.shape == [1, 10]

    def test_resnet50_param_count(self):
        net = pt.vision.models.resnet50()
        n = sum(int(np.prod(p.shape)) for p in net.parameters())
        assert abs(n - 25.557e6) / 25.557e6 < 0.01  # torchvision ~25.56M

    def test_mobilenet_v2(self):
        net = pt.vision.models.mobilenet_v2(num_classes=4)
        net.eval()
        assert net(pt.randn([1, 3, 64, 64])).shape == [1, 4]

    def test_mobilenet_v3_small(self):
        net = pt.vision.models.mobilenet_v3_small(num_classes=4)
        net.eval()
        assert net(pt.randn([1, 3, 64, 64])).shape == [1, 4]

    def test_vgg11(self):
        net = pt.vision.models.vgg11(num_classes=5)
        net.eval()
        assert net(pt.randn([1, 3, 224, 224])).shape == [1, 5]

    def test_squeezenet(self):
        net = pt.vision.models.squeezenet1_1(num_classes=7)
        net.eval()
        assert net(pt.randn([1, 3, 64, 64])).shape == [1, 7]

    def test_shufflenet(self):
        net = pt.vision.models.shufflenet_v2_x0_5(num_classes=6)
        net.eval()
        assert net(pt.randn([1, 3, 64, 64])).shape == [1, 6]

    def test_densenet121(self):
        net = pt.vision.models.densenet121(num_classes=3)
        net.eval()
        assert net(pt.randn([1, 3, 64, 64])).shape == [1, 3]

    def test_googlenet(self):
        net = pt.vision.models.googlenet(num_classes=4)
        net.eval()
        out, a1, a2 = net(pt.randn([1, 3, 64, 64]))
        assert out.shape == [1, 4]

    def test_alexnet(self):
        net = pt.vision.models.alexnet(num_classes=5)
        net.eval()
        assert net(pt.randn([1, 3, 224, 224])).shape == [1, 5]


class TestVisionTransformsDatasets:
    def test_vit_forward_backward(self):
        net = pt.vision.models.VisionTransformer(
            img_size=32, patch_size=8, embed_dim=32, depth=2, num_heads=4,
            num_classes=5)
        x = pt.randn([2, 3, 32, 32])
        out = net(x)
        assert out.shape == [2, 5]
        loss = pt.nn.CrossEntropyLoss()(out, pt.to_tensor(np.array([0, 3])))
        loss.backward()
        g = net.patch_embed.proj.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()

    def test_vit_b16_param_count(self):
        net = pt.vision.models.vit_b_16()
        n = sum(int(np.prod(p.shape)) for p in net.parameters())
        assert abs(n - 86.6e6) / 86.6e6 < 0.01  # ViT-B/16 ~86.6M

    def test_swin_forward(self):
        net = pt.vision.models.SwinTransformer(
            img_size=56, patch_size=4, embed_dim=24, depths=(1, 1),
            num_heads=(2, 4), window_size=7, num_classes=6)
        out = net(pt.randn([2, 3, 56, 56]))
        assert out.shape == [2, 6]
        assert np.isfinite(out.numpy()).all()

    def test_swin_shifted_window_masks_cross_region(self):
        # tokens moved together by the cyclic shift must not attend across
        # original image regions: verify the additive mask blocks them
        from paddle_tpu.vision.models.transformer_vision import SwinBlock
        blk = SwinBlock(8, 2, window_size=4, shift=2, input_resolution=(8, 8))
        m = blk._mask.numpy()   # (nW, N, N)
        assert m.shape[0] == 4 and (m < 0).any()
        # mask rows are symmetric: blocked pairs blocked both ways
        assert np.allclose(m, np.swapaxes(m, 1, 2))

    def test_convnext_forward_backward(self):
        net = pt.vision.models.ConvNeXt(depths=(1, 1, 1, 1),
                                        dims=(8, 16, 24, 32), num_classes=3)
        x = pt.randn([2, 3, 32, 32])
        out = net(x)
        assert out.shape == [2, 3]
        loss = out.sum()
        loss.backward()
        g = net.stages[0][0].dwconv.weight.grad
        assert g is not None and np.isfinite(g.numpy()).all()

    def test_transform_pipeline(self):
        from paddle_tpu.vision import transforms as T
        t = T.Compose([T.Resize(32), T.CenterCrop(28),
                       T.RandomHorizontalFlip(0.5),
                       T.ToTensor(), T.Normalize(0.5, 0.5)])
        img = np.random.randint(0, 255, (40, 50, 3)).astype(np.uint8)
        out = t(img)
        assert list(out.shape) == [3, 28, 28]

    def test_mnist_synthetic(self):
        from paddle_tpu.vision.datasets import MNIST
        ds = MNIST(mode="test")
        img, label = ds[0]
        assert img.shape[-2:] == (28, 28)
        assert 0 <= int(label) < 10

    def test_dataset_with_loader(self):
        from paddle_tpu.vision.datasets import Cifar10
        from paddle_tpu.vision import transforms as T
        ds = Cifar10(mode="test", transform=T.Compose([T.ToTensor()]))
        dl = pt.io.DataLoader(ds, batch_size=8)
        x, y = next(iter(dl))
        assert x.shape == [8, 3, 32, 32]

    def test_yolo_box_decode(self):
        from paddle_tpu.vision import ops as V
        rng = np.random.default_rng(0)
        x = rng.standard_normal((1, 2 * 8, 2, 2)).astype(np.float32)
        img = np.array([[64, 64]], np.int64)
        boxes, scores = V.yolo_box(pt.to_tensor(x), pt.to_tensor(img),
                                   [10, 14, 23, 27], 3, 0.01, 32)
        assert boxes.shape == [1, 8, 4] and scores.shape == [1, 8, 3]
        p = x.reshape(2, 8, 2, 2)
        sig = lambda v: 1 / (1 + np.exp(-v))
        bx = sig(p[0, 0, 0, 0]) / 2
        bw = np.exp(p[0, 2, 0, 0]) * 10 / 64
        x1 = np.clip((bx - bw / 2) * 64, 0, 63)
        got = boxes.numpy().reshape(2, 2, 2, 4)[0, 0, 0]
        if sig(p[0, 4, 0, 0]) > 0.01:
            assert abs(got[0] - x1) < 1e-4

    def test_deform_conv_zero_offset_equals_conv(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.vision import ops as V
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 4, 8, 8)).astype(np.float32)
        w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32)
        off = np.zeros((2, 18, 8, 8), np.float32)
        out = V.deform_conv2d(pt.to_tensor(x), pt.to_tensor(off),
                              pt.to_tensor(w), padding=1)
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        assert np.abs(out.numpy() - np.asarray(ref)).max() < 1e-3
        # modulated (v2): constant 0.5 mask halves the output
        msk = np.full((2, 9, 8, 8), 0.5, np.float32)
        out2 = V.deform_conv2d(pt.to_tensor(x), pt.to_tensor(off),
                               pt.to_tensor(w), padding=1,
                               mask=pt.to_tensor(msk))
        assert np.allclose(out2.numpy(), 0.5 * out.numpy(), atol=1e-4)

    def test_psroi_pool(self):
        from paddle_tpu.vision import ops as V
        rng = np.random.default_rng(0)
        feat = rng.standard_normal((1, 8, 8, 8)).astype(np.float32)
        out = V.PSRoIPool(2, 1.0)(
            pt.to_tensor(feat),
            pt.to_tensor(np.array([[0., 0., 8., 8.]], np.float32)),
            pt.to_tensor(np.array([1])))
        f = feat.reshape(2, 2, 2, 8, 8)
        assert np.allclose(out.numpy()[0, :, 0, 0],
                           f[:, 0, 0, 0:4, 0:4].mean(axis=(1, 2)), atol=1e-5)

    def test_generate_proposals(self):
        from paddle_tpu.vision import ops as V
        rng = np.random.default_rng(0)
        A, H, W = 3, 4, 4
        sc = rng.random((1, A, H, W)).astype(np.float32)
        bd = (rng.standard_normal((1, 4 * A, H, W)) * 0.1).astype(np.float32)
        anc = np.zeros((H, W, A, 4), np.float32)
        for yy in range(H):
            for xx in range(W):
                for aa in range(A):
                    anc[yy, xx, aa] = [xx * 8, yy * 8, xx * 8 + 16 + 8 * aa,
                                       yy * 8 + 16 + 8 * aa]
        var = np.full((H, W, A, 4), 1.0, np.float32)
        rois, rsc, rn = V.generate_proposals(
            pt.to_tensor(sc), pt.to_tensor(bd),
            pt.to_tensor(np.array([[32., 32.]])), pt.to_tensor(anc),
            pt.to_tensor(var), return_rois_num=True)
        assert rois.shape[0] == int(rn.numpy()[0]) > 0
        b = rois.numpy()
        assert (b[:, 2] >= b[:, 0]).all() and (b[:, :2] >= 0).all()

    def test_nms(self):
        from paddle_tpu.vision.ops import nms
        boxes = pt.to_tensor(np.array([[0, 0, 10, 10], [1, 1, 11, 11],
                                       [50, 50, 60, 60]], np.float32))
        scores = pt.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
        keep = nms(boxes, iou_threshold=0.5, scores=scores)
        assert keep.numpy().tolist() == [0, 2]


class TestJit:
    def test_to_static_function(self):
        @pt.jit.to_static
        def f(x):
            return x * 2 + 1
        out = f(pt.to_tensor([1.0, 2.0]))
        assert out.numpy().tolist() == [3.0, 5.0]

    def test_to_static_layer_matches_eager(self):
        net = pt.nn.Sequential(pt.nn.Linear(4, 8), pt.nn.GELU(),
                               pt.nn.Linear(8, 2))
        x = pt.randn([3, 4])
        eager = net(x).numpy()
        snet = pt.jit.to_static(net)
        static = snet(x).numpy()
        assert np.allclose(eager, static, atol=1e-6)

    def test_static_cache_reuse(self):
        calls = []

        @pt.jit.to_static
        def f(x):
            calls.append(1)
            return x + 1
        f(pt.randn([2, 2]))
        f(pt.randn([2, 2]))  # same shape → no retrace
        assert len(calls) == 1
        f(pt.randn([3, 2]))  # new shape → retrace
        assert len(calls) == 2


class TestDistribution:
    def test_normal(self):
        d = pt.distribution.Normal(0.0, 1.0)
        s = d.sample([1000])
        assert abs(float(s.numpy().mean())) < 0.2
        lp = d.log_prob(pt.to_tensor(0.0))
        assert np.allclose(float(lp), -0.5 * np.log(2 * np.pi), atol=1e-5)
        assert np.allclose(float(d.entropy()),
                           0.5 * np.log(2 * np.pi * np.e), atol=1e-5)

    def test_categorical_bernoulli(self):
        c = pt.distribution.Categorical(logits=pt.to_tensor([0.0, 0.0, 10.0]))
        assert int(c.sample([1]).numpy()[0]) == 2
        b = pt.distribution.Bernoulli(pt.to_tensor(0.3))
        assert np.allclose(float(b.log_prob(pt.to_tensor(1.0))),
                           np.log(0.3), atol=1e-5)

    def test_kl(self):
        p = pt.distribution.Normal(0.0, 1.0)
        q = pt.distribution.Normal(1.0, 1.0)
        assert np.allclose(float(pt.distribution.kl_divergence(p, q)), 0.5,
                           atol=1e-5)

    def test_transformed(self):
        base = pt.distribution.Normal(0.0, 1.0)
        d = pt.distribution.TransformedDistribution(
            base, [pt.distribution.ExpTransform()])
        x = d.sample([10])
        assert (x.numpy() > 0).all()
        ln = pt.distribution.LogNormal(0.0, 1.0)
        v = pt.to_tensor(2.0)
        assert np.allclose(float(d.log_prob(v)), float(ln.log_prob(v)),
                           atol=1e-4)

    def test_mvn_studentt_chi2_binomial(self):
        import scipy.stats as ss
        D = pt.distribution
        mu = np.array([1.0, -2.0], np.float32)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        mvn = D.MultivariateNormal(pt.to_tensor(mu),
                                   covariance_matrix=pt.to_tensor(cov))
        x = np.array([0.5, -1.0], np.float32)
        assert abs(float(mvn.log_prob(pt.to_tensor(x))) -
                   ss.multivariate_normal(mu, cov).logpdf(x)) < 1e-4
        st = D.StudentT(pt.to_tensor(5.0), pt.to_tensor(1.0),
                        pt.to_tensor(2.0))
        assert abs(float(st.log_prob(pt.to_tensor(0.5))) -
                   ss.t(5, 1, 2).logpdf(0.5)) < 1e-5
        c2 = D.Chi2(pt.to_tensor(4.0))
        assert abs(float(c2.log_prob(pt.to_tensor(3.0))) -
                   ss.chi2(4).logpdf(3.0)) < 1e-4
        b = D.Binomial(pt.to_tensor(10.0), pt.to_tensor(0.3))
        assert abs(float(b.log_prob(pt.to_tensor(4.0))) -
                   ss.binom(10, 0.3).logpmf(4)) < 1e-5

    def test_independent_and_transforms(self):
        import scipy.stats as ss
        D = pt.distribution
        ind = D.Independent(
            D.Normal(pt.to_tensor(np.zeros(3, np.float32)),
                     pt.to_tensor(np.ones(3, np.float32))), 1)
        lp = float(ind.log_prob(pt.to_tensor(np.zeros(3, np.float32))))
        assert abs(lp - 3 * ss.norm.logpdf(0)) < 1e-5
        td = D.TransformedDistribution(
            D.Normal(pt.to_tensor(0.0), pt.to_tensor(1.0)),
            [D.TanhTransform()])
        y = 0.5
        expect = ss.norm.logpdf(np.arctanh(y)) - np.log1p(-y * y)
        assert abs(float(td.log_prob(pt.to_tensor(y))) - expect) < 1e-4
        sb = D.StickBreakingTransform()
        v = np.array([0.3, -0.7, 1.1], np.float32)
        simplex = sb.forward(pt.to_tensor(v))
        assert abs(float(simplex.numpy().sum()) - 1.0) < 1e-5
        assert np.allclose(sb.inverse(simplex).numpy(), v, atol=1e-4)

    def test_gamma_beta_dirichlet(self):
        g = pt.distribution.Gamma(2.0, 3.0)
        assert np.isfinite(float(g.log_prob(pt.to_tensor(1.0))))
        be = pt.distribution.Beta(2.0, 2.0)
        assert np.allclose(float(be.mean), 0.5)
        dr = pt.distribution.Dirichlet(pt.to_tensor([1.0, 1.0, 1.0]))
        s = dr.sample()
        assert np.allclose(s.numpy().sum(), 1.0, atol=1e-5)


class TestSparseFFT:
    def test_sparse_coo(self):
        idx = pt.to_tensor(np.array([[0, 1], [1, 2]]))
        vals = pt.to_tensor(np.array([3.0, 4.0], np.float32))
        sp = pt.sparse.sparse_coo_tensor(idx, vals, [2, 3])
        dense = sp.to_dense().numpy()
        assert dense[0, 1] == 3.0 and dense[1, 2] == 4.0
        y = pt.sparse.matmul(sp, pt.ones([3, 2]))
        assert y.shape == [2, 2]

    def test_sparse_unary_binary(self):
        sp = pt.sparse
        x = sp.sparse_coo_tensor([[0, 0, 1, 2], [0, 2, 1, 0]],
                                 [1.0, -2.0, 3.0, -4.0], shape=[3, 3])
        d = x.to_dense().numpy()
        assert np.allclose(sp.abs(x).to_dense().numpy(), np.abs(d))
        assert np.allclose(sp.tanh(x).to_dense().numpy(), np.tanh(d))
        assert np.allclose(sp.relu(x).to_dense().numpy(), np.maximum(d, 0))
        y = sp.sparse_coo_tensor([[0, 0, 1, 2], [0, 2, 1, 0]],
                                 [1.0, 1.0, 1.0, 1.0], shape=[3, 3])
        assert np.allclose(sp.add(x, y).to_dense().numpy(),
                           d + y.to_dense().numpy())
        assert sp.nnz(x) == 4

    def test_sparse_masked_matmul_softmax(self):
        sp = pt.sparse
        x = sp.sparse_coo_tensor([[0, 0, 1, 2], [0, 2, 1, 0]],
                                 [1.0, -2.0, 3.0, -4.0], shape=[3, 3])
        y = sp.sparse_coo_tensor([[0, 0, 1, 2], [0, 2, 1, 0]],
                                 [1.0, 1.0, 1.0, 1.0], shape=[3, 3])
        rng = np.random.default_rng(1)
        a = pt.to_tensor(rng.standard_normal((3, 4)).astype(np.float32))
        b = pt.to_tensor(rng.standard_normal((4, 3)).astype(np.float32))
        mm = sp.masked_matmul(a, b, y)
        full = a.numpy() @ b.numpy()
        assert np.allclose(mm.to_dense().numpy(),
                           np.where(y.to_dense().numpy() != 0, full, 0),
                           atol=1e-5)
        sm = sp.softmax(x)
        row0 = np.exp(np.array([1.0, -2.0]) - 1.0)
        row0 /= row0.sum()
        assert np.allclose(sm.values().numpy()[:2], row0, atol=1e-6)
        # transforms
        d = x.to_dense().numpy()
        assert np.allclose(sp.transpose(x, [1, 0]).to_dense().numpy(), d.T)
        assert np.allclose(sp.reshape(x, [9]).to_dense().numpy(),
                           d.reshape(9))

    def test_fft_matches_numpy(self):
        x = np.random.randn(32).astype(np.float32)
        ours = pt.fft.rfft(pt.to_tensor(x)).numpy()
        ref = np.fft.rfft(x)
        assert np.allclose(ours, ref, atol=1e-4)


class TestIncubate:
    def test_fused_rms_norm(self):
        from paddle_tpu.incubate.nn import functional as FI
        x = pt.randn([2, 8])
        w = pt.ones([8])
        out = FI.fused_rms_norm(x, w)
        ref = pt.nn.functional.rms_norm(x, w)
        assert np.allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_fused_rope(self):
        from paddle_tpu.incubate.nn import functional as FI
        from paddle_tpu.ops.rope import rope_cos_sin
        import jax.numpy as jnp
        q = pt.randn([2, 4, 8, 16])  # B,H,S,D
        cos, sin = rope_cos_sin(8, 16)
        qo, ko, vo = FI.fused_rotary_position_embedding(
            q, q, None, sin=pt.to_tensor(sin), cos=pt.to_tensor(cos))
        assert qo.shape == [2, 4, 8, 16]

    def test_fused_linear_cross_entropy(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.fused import fused_linear_cross_entropy
        rng = np.random.default_rng(0)
        N, H, V = 8, 16, 300
        x = jnp.asarray(rng.standard_normal((N, H)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((H, V)).astype(np.float32) * 0.1)
        y = jnp.asarray(rng.integers(0, V, (N,)))

        def ref(x, w):
            logits = x @ w
            lse = jax.scipy.special.logsumexp(logits, axis=1)
            return jnp.mean(lse - logits[jnp.arange(N), y])

        f = lambda x, w: fused_linear_cross_entropy(x, w, y, chunk_size=128)
        assert abs(float(f(x, w) - ref(x, w))) < 1e-5
        gf = jax.grad(f, argnums=(0, 1))(x, w)
        gr = jax.grad(ref, argnums=(0, 1))(x, w)
        for a, b in zip(gf, gr):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
        # incubate surface (eager Tensors)
        from paddle_tpu.incubate.nn import functional as IF
        out = IF.fused_linear_cross_entropy(
            pt.to_tensor(np.asarray(x)), pt.to_tensor(np.asarray(w)),
            pt.to_tensor(np.asarray(y)), chunk_size=64)
        assert abs(float(out) - float(ref(x, w))) < 1e-5

    def test_fused_moe_layer(self):
        from paddle_tpu.incubate.nn import FusedMoE
        moe = FusedMoE(16, 32, num_experts=4, top_k=2)
        out = moe(pt.randn([2, 6, 16]))
        assert out.shape == [2, 6, 16]


class TestProfilerTrace:
    def test_profiler_steps(self):
        prof = pt.profiler.Profiler(timer_only=True)
        prof.start()
        for _ in range(3):
            (pt.randn([10]) * 2).numpy()
            prof.step()
        prof.stop()
        assert "avg step" in prof.step_info()

    def test_trace_ring(self):
        from paddle_tpu.utils import trace
        trace.enable()
        trace.clear()
        trace.record("matmul", 0.001)
        trace.record("matmul", 0.002)
        assert "matmul" in trace.summary()
        trace.disable()


class TestStaticFacade:
    def test_program_executor(self):
        exe = pt.static.Executor()
        x = pt.to_tensor([1.0, 2.0])
        y = x * 3
        out = exe.run(fetch_list=[y])
        assert np.allclose(out[0], [3.0, 6.0])

    def test_executor_honors_feed(self):
        """Executor.run(feed=...) replays the recorded graph with the fed
        placeholder values — not just returns stale fetches."""
        prog = pt.static.Program()
        with pt.static.program_guard(prog):
            x = pt.static.data("x", [None, 4])
            y = pt.static.nn.fc(x, 8, activation="relu")
        exe = pt.static.Executor()
        a = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        out1 = exe.run(prog, feed={"x": a}, fetch_list=[y])[0]
        assert out1.shape == (3, 8)
        out2 = exe.run(prog, feed={"x": 2 * a}, fetch_list=[y])[0]
        assert not np.allclose(out1, out2)  # feed actually changes results
        # pure elementwise graph (no trainables in the path) also replays
        with pt.static.program_guard(prog):
            z = pt.static.data("z", [2])
        w = z * 3
        outz = exe.run(prog, feed={"z": np.asarray([1.0, 2.0], np.float32)},
                       fetch_list=[w])[0]
        assert np.allclose(outz, [3.0, 6.0])
        # unknown feed name raises instead of being ignored
        import pytest
        with pytest.raises(KeyError):
            exe.run(prog, feed={"nope": a}, fetch_list=[y])

    def test_static_save_load_roundtrip(self, tmp_path):
        prog = pt.static.Program()
        with pt.static.program_guard(prog):
            x = pt.static.data("x", [None, 4])
            y = pt.static.nn.fc(x, 2)
        exe = pt.static.Executor()
        a = np.ones((1, 4), np.float32)
        before = exe.run(prog, feed={"x": a}, fetch_list=[y])[0]
        path = str(tmp_path / "model")
        pt.static.save(prog, path)
        # clobber the parameters, then restore
        for t in prog._params.values():
            t._replace(jnp.zeros_like(t._value))
        zeroed = exe.run(prog, feed={"x": a}, fetch_list=[y])[0]
        assert np.allclose(zeroed, 0)
        pt.static.load(prog, path)
        after = exe.run(prog, feed={"x": a}, fetch_list=[y])[0]
        assert np.allclose(before, after)
        # empty program refuses to "save"
        import pytest
        with pytest.raises(RuntimeError):
            pt.static.save(pt.static.Program(), str(tmp_path / "empty"))

    def test_cond_while_survive_jit(self):
        """static.nn.cond / while_loop lower to lax under tracing."""
        import jax

        def f(x):
            y = pt.static.nn.cond(x.sum() > 0,
                                  lambda: x * 2,
                                  lambda: x - 1)
            return y

        x = jnp.asarray([1.0, 2.0])
        eager = f(pt.to_tensor(np.asarray(x)))
        jitted = jax.jit(lambda a: pt.static.nn.cond(
            a.sum() > 0, lambda: a * 2, lambda: a - 1))(x)
        assert np.allclose(np.asarray(eager.numpy()), np.asarray(jitted))
        neg = jax.jit(lambda a: pt.static.nn.cond(
            a.sum() > 0, lambda: a * 2, lambda: a - 1))(-x)
        assert np.allclose(np.asarray(neg), np.asarray(-x - 1))

        def wl(n):
            i, acc = pt.static.nn.while_loop(
                lambda i, acc: i < n,
                lambda i, acc: (i + 1, acc + i),
                (jnp.asarray(0), jnp.asarray(0)))
            return acc

        out = jax.jit(wl)(jnp.asarray(5))
        assert int(np.asarray(_as_arr(out))) == 10

    def test_while_loop_eager(self):
        i, acc = pt.static.nn.while_loop(
            lambda i, acc: i < 4,
            lambda i, acc: (i + 1, acc + 2 * i),
            (pt.to_tensor(0), pt.to_tensor(0)))
        assert int(acc.numpy()) == 12


def _as_arr(x):
    return x._value if hasattr(x, "_value") else x


class TestYoloLossDeformGroups:
    def test_yolo_loss_matches_numpy_reference(self):
        """yolo_loss vs an independent numpy YOLOv3 loss (reference
        semantics: phi yolo_v3_loss kernel — SCE xy, L1 wh with size
        scale, ignore-thresh objectness, smoothed class BCE)."""
        from paddle_tpu.vision.ops import yolo_loss
        rng = np.random.default_rng(0)
        N, H, W, nc = 2, 4, 4, 3
        anchors = [10, 13, 16, 30, 33, 23]
        mask = [0, 1, 2]
        na = 3
        down = 8
        x = rng.standard_normal((N, na * (5 + nc), H, W)).astype(np.float32)
        gt = np.zeros((N, 3, 4), np.float32)
        gt[0, 0] = [0.3, 0.4, 0.2, 0.3]
        gt[0, 1] = [0.7, 0.2, 0.5, 0.5]
        gt[1, 0] = [0.5, 0.5, 0.1, 0.8]
        lbl = np.array([[1, 2, 0], [0, 0, 0]], np.int64)

        out = yolo_loss(pt.to_tensor(x), pt.to_tensor(gt), pt.to_tensor(lbl),
                        anchors, mask, nc, ignore_thresh=0.5,
                        downsample_ratio=down).numpy()

        # independent numpy implementation
        def sig(v):
            return 1 / (1 + np.exp(-v))

        def bce(logit, label):
            return np.maximum(logit, 0) - logit * label + \
                np.log1p(np.exp(-np.abs(logit)))

        anc = np.asarray(anchors, np.float32).reshape(-1, 2)
        in_w, in_h = down * W, down * H
        p = x.reshape(N, na, 5 + nc, H, W)
        smooth = 1.0 / max(nc, 40)
        on, off = 1 - smooth, smooth
        ref = np.zeros(N)
        for n in range(N):
            obj_m = np.zeros((na, H, W), bool)
            tgt = {}
            for b in range(gt.shape[1]):
                gx, gy, gw, gh = gt[n, b]
                if gw <= 1e-8:
                    continue
                ious = []
                for a in range(len(anc)):
                    iw = min(gw * in_w, anc[a, 0])
                    ih = min(gh * in_h, anc[a, 1])
                    inter = iw * ih
                    union = gw * in_w * gh * in_h + anc[a, 0] * anc[a, 1] - inter
                    ious.append(inter / union)
                best = int(np.argmax(ious))
                if best not in mask:
                    continue
                k = mask.index(best)
                gi, gj = int(gx * W), int(gy * H)
                obj_m[k, gj, gi] = True
                tgt[(k, gj, gi)] = (gx * W - gi, gy * H - gj,
                                    np.log(gw * in_w / anc[best, 0]),
                                    np.log(gh * in_h / anc[best, 1]),
                                    2 - gw * gh, lbl[n, b])
            # ignore mask from decoded preds
            loss = 0.0
            for k in range(na):
                aw, ah = anc[mask[k]]
                for j in range(H):
                    for i in range(W):
                        bx = (sig(p[n, k, 0, j, i]) + i) / W
                        by = (sig(p[n, k, 1, j, i]) + j) / H
                        bw = np.exp(p[n, k, 2, j, i]) * aw / in_w
                        bh = np.exp(p[n, k, 3, j, i]) * ah / in_h
                        best_iou = 0
                        for b in range(gt.shape[1]):
                            if gt[n, b, 2] <= 1e-8:
                                continue
                            b1 = [bx - bw / 2, by - bh / 2, bx + bw / 2, by + bh / 2]
                            g = gt[n, b]
                            b2 = [g[0] - g[2] / 2, g[1] - g[3] / 2,
                                  g[0] + g[2] / 2, g[1] + g[3] / 2]
                            iw = max(min(b1[2], b2[2]) - max(b1[0], b2[0]), 0)
                            ih = max(min(b1[3], b2[3]) - max(b1[1], b2[1]), 0)
                            inter = iw * ih
                            a1 = (b1[2] - b1[0]) * (b1[3] - b1[1])
                            a2 = (b2[2] - b2[0]) * (b2[3] - b2[1])
                            best_iou = max(best_iou, inter / (a1 + a2 - inter + 1e-10))
                        if obj_m[k, j, i]:
                            tx, ty, tw, th, sc, c = tgt[(k, j, i)]
                            loss += sc * (bce(p[n, k, 0, j, i], tx) +
                                          bce(p[n, k, 1, j, i], ty))
                            loss += sc * (abs(p[n, k, 2, j, i] - tw) +
                                          abs(p[n, k, 3, j, i] - th))
                            loss += bce(p[n, k, 4, j, i], 1.0)
                            for cc in range(nc):
                                t = on if cc == c else off
                                loss += bce(p[n, k, 5 + cc, j, i], t)
                        elif best_iou <= 0.5:
                            loss += bce(p[n, k, 4, j, i], 0.0)
            ref[n] = loss
        assert np.allclose(out, ref, rtol=1e-4, atol=1e-3), (out, ref)

    def test_deform_conv_groups(self):
        """groups>1: matches a plain grouped conv at zero offsets."""
        import jax
        import jax.numpy as jnp2
        from paddle_tpu.vision import ops as V
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 8, 6, 6)).astype(np.float32)
        w = rng.standard_normal((4, 4, 3, 3)).astype(np.float32)  # groups=2
        off = np.zeros((2, 18, 6, 6), np.float32)
        out = V.deform_conv2d(pt.to_tensor(x), pt.to_tensor(off),
                              pt.to_tensor(w), padding=1, groups=2)
        ref = jax.lax.conv_general_dilated(
            jnp2.asarray(x), jnp2.asarray(w), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"), feature_group_count=2)
        assert np.abs(out.numpy() - np.asarray(ref)).max() < 1e-3


class TestLKJCholesky:
    def test_log_prob_matches_torch(self):
        """reference: python/paddle/distribution/lkj_cholesky.py:128."""
        import torch
        from paddle_tpu.distribution import LKJCholesky
        pt.seed(0)
        for dim, conc in ((3, 1.0), (4, 2.5), (2, 0.7)):
            d = LKJCholesky(dim, conc)
            td = torch.distributions.LKJCholesky(dim, conc)
            Ls = td.sample((5,))
            ours = d.log_prob(pt.to_tensor(Ls.numpy())).numpy()
            theirs = td.log_prob(Ls).numpy()
            assert np.abs(ours - theirs).max() < 1e-4

    def test_samples_are_valid_cholesky(self):
        from paddle_tpu.distribution import LKJCholesky
        pt.seed(1)
        s = LKJCholesky(4, 1.5).sample((8,)).numpy()
        assert s.shape == (8, 4, 4)
        C = s @ np.swapaxes(s, -1, -2)
        assert np.allclose(np.diagonal(C, axis1=-2, axis2=-1), 1.0, atol=1e-5)
        assert np.allclose(s, np.tril(s))
        assert (np.linalg.eigvalsh(C) > -1e-6).all()


class TestSparseSoftmax3D:
    def test_batched_3d_matches_masked_dense(self):
        """sparse softmax beyond 2D (batched): nonzeros of each (i, j, :)
        row normalize among themselves."""
        sp = pt.sparse
        rng = np.random.RandomState(0)
        dense = rng.randn(2, 4, 5).astype(np.float32)
        mask = rng.rand(2, 4, 5) < 0.5
        mask[0, 0] = True  # at least one full row
        idx = np.stack(np.nonzero(mask))
        vals = dense[mask]
        x = sp.sparse_coo_tensor(idx, vals, shape=[2, 4, 5])
        out = sp.softmax(x).to_dense().numpy()
        ref = np.zeros_like(dense)
        for i in range(2):
            for j in range(4):
                nz = mask[i, j]
                if nz.any():
                    e = np.exp(dense[i, j, nz] - dense[i, j, nz].max())
                    ref[i, j, nz] = e / e.sum()
        assert np.abs(out - ref).max() < 1e-5


class TestLegacyReaderAPI:
    """paddle.batch / paddle.reader decorators (reference python/paddle/
    batch.py + reader/decorator.py)."""

    def test_batch(self):
        r = pt.batch(lambda: iter(range(10)), batch_size=3)
        assert list(r()) == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
        r = pt.batch(lambda: iter(range(10)), batch_size=3, drop_last=True)
        assert list(r()) == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]

    def test_decorators(self):
        rd = pt.reader
        base = lambda: iter(range(6))
        assert list(rd.firstn(base, 3)()) == [0, 1, 2]
        assert list(rd.chain(base, base)()) == list(range(6)) * 2
        assert list(rd.map_readers(lambda a, b: a + b, base, base)()) == \
            [0, 2, 4, 6, 8, 10]
        assert list(rd.compose(base, rd.map_readers(lambda x: (x, -x),
                                                    base))()) == \
            [(i, i, -i) for i in range(6)]
        assert sorted(rd.shuffle(base, 4)()) == list(range(6))
        assert list(rd.buffered(base, 2)()) == list(range(6))
        c = rd.cache(base)
        assert list(c()) == list(range(6)) and list(c()) == list(range(6))

    def test_xmap_and_multiprocess(self):
        rd = pt.reader
        base = lambda: iter(range(20))
        out = list(rd.xmap_readers(lambda x: x * x, base, 4, 8,
                                   order=True)())
        assert out == [i * i for i in range(20)]
        out = sorted(rd.xmap_readers(lambda x: x * x, base, 4, 8)())
        assert out == sorted(i * i for i in range(20))
        out = sorted(rd.multiprocess_reader([base, base])())
        assert out == sorted(list(range(20)) * 2)

    def test_sysconfig(self):
        import os
        assert os.path.isdir(pt.sysconfig.get_include())
        assert os.path.isdir(pt.sysconfig.get_lib())

    def test_cache_partial_epoch_no_dup(self):
        c = pt.reader.cache(lambda: iter(range(4)))
        next(c())  # abandon mid-epoch
        assert list(c()) == [0, 1, 2, 3]
        assert list(c()) == [0, 1, 2, 3]

    def test_xmap_mapper_error_propagates(self):
        import pytest as _pytest

        def bad(x):
            raise ValueError("boom")

        with _pytest.raises(ValueError, match="boom"):
            list(pt.reader.xmap_readers(bad, lambda: iter(range(4)), 2, 4)())

    def test_batch_size_validation(self):
        import pytest as _pytest
        with _pytest.raises(ValueError):
            pt.batch(lambda: iter(range(3)), batch_size=0)

    def test_buffered_and_multiprocess_reader_errors_propagate(self):
        import pytest as _pytest

        def bad():
            yield 1
            raise IOError("disk gone")

        with _pytest.raises(IOError):
            list(pt.reader.buffered(bad, 2)())
        with _pytest.raises(IOError):
            list(pt.reader.multiprocess_reader([bad, lambda: iter(range(3))])())
        with _pytest.raises(IOError):
            list(pt.reader.xmap_readers(lambda x: x, bad, 2, 4)())

    def test_legacy_dataset_readers(self):
        """paddle.dataset parity (reference python/paddle/dataset/*):
        reader-style .train()/.test() backed by the modern datasets."""
        import itertools
        r = pt.dataset.mnist.train()
        x, y = next(iter(r()))
        assert x.shape == (784,) and 0 <= y < 10
        assert -1.0 <= x.min() and x.max() <= 1.0
        b = next(pt.batch(pt.dataset.mnist.test(), 16)())
        assert len(b) == 16
        feats, target = next(iter(pt.dataset.uci_housing.train()()))
        assert feats.shape[-1] == 13
        assert len(list(itertools.islice(pt.dataset.cifar.train10()(), 2))) == 2

    def test_utils_deprecated(self):
        import warnings
        from paddle_tpu.utils import deprecated

        @deprecated(update_to="pt.new_api", since="2.0", level=1)
        def old(x):
            return x + 1

        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert old(1) == 2
            assert any(issubclass(i.category, DeprecationWarning) for i in w)
