"""THE tunnel-aliveness canary — single source for every prober
(tools/tpu_watch.sh, tools/tpu_capture.sh, tools/autotune._tunnel_alive).

Exit 0 iff the tunnel can compile AND execute right now:
  * persistent compilation cache disabled BEFORE importing jax, so a
    disk-cache hit can never mask a dead remote-compile service (the
    2026-07-31 "half-alive" mode: devices list fine, every compile
    burns its full timeout);
  * the canary VALUE is random, so the serving terminal's
    (executable, inputs) -> output memoization can never mask a dead
    execute service with a cached answer.

Callers must wrap in a timeout (a dead tunnel hangs device init):
    timeout 180 python tools/_tpu_canary.py
"""
import os
import random
import sys

os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    if jax.devices()[0].platform != "tpu":
        print("canary: not a TPU platform", file=sys.stderr)
        return 1
    n = random.randrange(1, 100000)
    x = jnp.full((2, 1024), n, jnp.int32)
    got = int(jax.jit(lambda a: (a * 2).sum())(x))
    if got != 4096 * n:
        print(f"canary: wrong result {got} != {4096 * n}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
