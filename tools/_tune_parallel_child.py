"""Stage-D trial child: time ONE parallel placement on the virtual
CPU mesh (no hardware needed — parity with the reference auto_tuner's
searched-configs runs, /root/reference/python/paddle/distributed/
auto_tuner/search.py, which launches real trial jobs).

Env:
  PT_TUNE_PAR_CFG   json {dp, tp, pp, n_micro, schedule, vpp, zero,
                          fused_ce}
  PT_TUNE_PAR_NDEV  virtual device count (default 8)
  PT_TUNE_PAR_SIZE  "tiny" (tests) | "small" (default search size)

Prints one JSON line {"step_time_s": float, "cfg": {...}}.
Exit non-zero on any failure (OOM-equivalent, bad mesh, compile error)
— the parent scores only clean trials.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    cfg = json.loads(os.environ["PT_TUNE_PAR_CFG"])
    ndev = int(os.environ.get("PT_TUNE_PAR_NDEV", "8"))
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count="
                               f"{ndev}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.models import llama_spmd as M
    from paddle_tpu.parallel.mesh import create_mesh, fsdp_spec

    size = os.environ.get("PT_TUNE_PAR_SIZE", "small")
    if size == "tiny":
        mcfg = LlamaConfig.tiny(vocab=128, hidden=64, layers=8, heads=4,
                                kv_heads=4, ffn=128)
        batch, seq, iters = 8, 32, 2
    else:
        mcfg = LlamaConfig.tiny(vocab=1024, hidden=256, layers=8, heads=8,
                                kv_heads=8, ffn=704)
        batch, seq, iters = 8, 128, 3

    dp, tp, pp = cfg.get("dp", 1), cfg.get("tp", 1), cfg.get("pp", 1)
    axes = {}
    if pp > 1:
        axes["pp"] = pp
    axes["dp"] = dp
    if tp > 1:
        axes["tp"] = tp
    mesh = create_mesh(axes, devices=jax.devices()[:dp * tp * pp])

    params = M.init_params(mcfg, seed=0)
    if cfg.get("zero") and pp == 1 and tp == 1:
        # ZeRO-3 placement: every param fsdp-sharded over dp; GSPMD
        # inserts the all-gathers/reduce-scatters. make_train_step pins
        # its own (megatron) in_shardings, so build the step directly
        # (mirrors __graft_entry__'s ZeRO dryrun step).
        params = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(
                mesh, fsdp_spec(a.shape, mesh, "dp"))), params)
        opt = M.init_opt_state(params)
        fused = bool(cfg.get("fused_ce"))

        def z_loss(p, batch):
            return M.loss_fn(p, batch, mcfg, mesh=None, remat=False,
                             fused_ce=fused)

        @jax.jit
        def step(p, o, i, batch):
            loss, g = jax.value_and_grad(z_loss)(p, batch)
            p2, o2 = M.adamw_update(p, g, o, 1e-3, i.astype(jnp.float32))
            return p2, o2, loss
    else:
        if pp > 1:
            params = M.place_params(params, mcfg, mesh)
        opt = M.init_opt_state(params)
        kw = {}
        if pp > 1:
            kw["schedule"] = cfg.get("schedule", "1f1b")
            if kw["schedule"] == "interleave":
                kw["vpp"] = cfg.get("vpp", 2)
        step = M.make_train_step(mcfg, mesh,
                                 n_micro=cfg.get("n_micro") or None,
                                 remat=False, donate=False,
                                 fused_ce=bool(cfg.get("fused_ce")),
                                 lr=1e-3, **kw)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, mcfg.vocab_size, (batch, seq)))
    y = jnp.asarray(rng.randint(0, mcfg.vocab_size, (batch, seq)))
    params, opt, loss = step(params, opt, jnp.asarray(0), (x, y))  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for i in range(iters):
        params, opt, loss = step(params, opt, jnp.asarray(i + 1), (x, y))
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / iters
    assert np.isfinite(float(loss)), f"loss diverged: {loss}"
    print(json.dumps({"step_time_s": round(dt, 5), "cfg": cfg}))


if __name__ == "__main__":
    main()
