"""Stub bench child for autotune.py's smoke mode (PT_TUNE_SMOKE=1).

Reads the same PT_BENCH_* / PT_FLASH_* env knobs a real bench.py child
would, and answers with a deterministic fake tok/s landscape that has a
single known peak — so tests can assert the staged search actually
finds it.  Fault injection via PT_SMOKE_FAULT exercises every guard in
run_trial():

  cpu     — emit backend:"cpu" (tunnel-died fallback)
  pallas  — emit pallas_fallback:true (Mosaic rejection path)
  crash   — exit non-zero with noise on stderr
  garbage — exit 0 but print no parseable JSON line
  hang    — sleep past the trial timeout

PT_SMOKE_FAULT_BLOCK_Q, if set, applies the fault only to trials at
that block_q — lets a test poison one stage-B config while the rest of
the search proceeds.
"""
import json
import os
import sys
import time


def main():
    batch = int(os.environ.get("PT_BENCH_BATCH", "16"))
    seq = int(os.environ.get("PT_BENCH_SEQ", "2048"))
    remat = os.environ.get("PT_BENCH_REMAT", "true")
    bq = int(os.environ.get("PT_FLASH_BLOCK_Q", "128"))
    bk = int(os.environ.get("PT_FLASH_BLOCK_K", "128"))
    nm = int(os.environ.get("PT_BENCH_NMICRO", "0"))
    fce = os.environ.get("PT_FUSED_CE", "0") == "1"

    fault = os.environ.get("PT_SMOKE_FAULT", "")
    only_bq = os.environ.get("PT_SMOKE_FAULT_BLOCK_Q")
    if fault and (only_bq is None or int(only_bq) == bq):
        if fault == "hang":
            time.sleep(3600)
        if fault == "crash":
            print("fake Mosaic OOM: exhausted VMEM", file=sys.stderr)
            sys.exit(7)
        if fault == "garbage":
            print("no json here, just vibes")
            return
        extra = {"backend": "cpu"} if fault == "cpu" else \
            {"backend": "tpu", "pallas_fallback": True}
        extra.setdefault("mfu", 0.01)
        print(json.dumps({"metric": "smoke", "value": 1.0, "unit": "tok/s",
                          "vs_baseline": 0.0, "extra": extra}))
        return

    # Deterministic landscape, peaked at batch=64, remat=true,
    # fused_ce=True, n_micro=2, (block_q, block_k)=(256, 512) — the
    # shape the first honest on-chip stage-A pass suggested (2026-08-01:
    # full-remat MFU climbs with batch, dots disappoints, the grad-accum
    # corner wins at the HBM wall).  Tests assert the staged search
    # lands exactly there.
    v = 10_000.0
    v += {8: 100, 16: 500, 24: 1400, 32: 1500, 40: 1700,
          48: 2000, 64: 2200}.get(batch, 0)
    v += {"true": 800, "dots": 600, "false": 400}.get(remat, 0)
    v += 1200 if fce else 0
    v += {(128, 128): 0, (256, 256): 600, (256, 512): 900,
          (512, 256): 300, (512, 512): 500}.get((bq, bk), 0)
    v += {0: 0, 2: 250, 4: -400}.get(nm, 0)
    mfu = round(v / 58_000.0, 4)
    print(json.dumps({
        "metric": f"smoke llama-{seq}x{batch}", "value": v,
        "unit": "tok/s", "vs_baseline": 0.0,
        "extra": {"backend": "tpu", "mfu": mfu,
                  "mfu_legacy": round(mfu * 1.13, 4)}}))


if __name__ == "__main__":
    main()
