"""TPU-native auto-tuner (parity: reference auto_tuner subsystem,
/root/reference/python/paddle/distributed/auto_tuner/tuner.py — a
parallel-config/batch search harness; ours searches the knobs that
matter on one TPU chip and persists the winner).

Staged search over (batch, remat policy, fused linear+CE head, flash
block_q/block_k, n_micro) for the headline Llama pretrain step:

  stage A: batch x remat x fused_ce coarse grid
  stage B: flash block sizes at the stage-A winner
  stage C: grad-accum microbatching at the stage-B winner

Every trial is a guarded `bench.py` child (so a Mosaic rejection or OOM
kills the trial, not the tuner) and appends to BENCH_HISTORY.jsonl via
bench.py's own history hook.  The winner is written to TUNED.json after
every stage (partial progress survives a mid-search tunnel death), and
bench.py reads TUNED.json as its defaults.

Run on a live chip:  python tools/autotune.py

Smoke mode (no hardware): PT_TUNE_SMOKE=1 skips the TPU-alive probe and
runs the full stage-A/B/C search against a stub child
(tools/_tune_smoke_child.py by default) that answers with deterministic
fake numbers — so the tuner's parsing, guards, dedup, and persistence
are all proven BEFORE its first unattended run on a real tunnel window.
Smoke results are written to TUNED.smoke.json (or $PT_TUNE_OUT), never
to the TUNED.json that bench.py reads as defaults.

Env knobs:
  PT_TUNE_SMOKE=1   — smoke mode (see above)
  PT_TUNE_CHILD     — path to the per-trial child script
  PT_TUNE_OUT       — output path override for the winner JSON
  PT_TUNE_TRIAL_TIMEOUT — per-trial wall clock (seconds)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
SMOKE = os.environ.get("PT_TUNE_SMOKE") == "1"
# Smoke output must NEVER land on the TUNED.json bench.py reads as
# defaults — fake numbers as real defaults would poison the next
# on-chip bench.
TUNED = os.environ.get("PT_TUNE_OUT") or os.path.join(
    ROOT, "TUNED.smoke.json" if SMOKE else "TUNED.json")
_DEFAULT_CHILD = os.path.join(HERE, "_tune_smoke_child.py") if SMOKE \
    else os.path.join(ROOT, "bench.py")
CHILD = os.environ.get("PT_TUNE_CHILD") or _DEFAULT_CHILD

TRIAL_TIMEOUT = int(os.environ.get("PT_TUNE_TRIAL_TIMEOUT", "600"))


def _load_defaults():
    import importlib.util
    p = os.path.join(ROOT, "paddle_tpu", "_tuning_defaults.py")
    spec = importlib.util.spec_from_file_location("_tuning_defaults", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_TD = _load_defaults()


def _resolved(cfg):
    """Dedup key over EFFECTIVE knobs: {batch,seq,remat} and the same
    cfg with explicit default block/n_micro values build identical
    child environments and must not be measured twice."""
    return (cfg["batch"], cfg["seq"], str(cfg["remat"]).lower(),
            bool(cfg.get("fused_ce"))) + _TD.effective_knobs(cfg)


def run_trial(cfg, trials):
    """One bench.py child at `cfg`; returns the parsed JSON line or None."""
    for t in trials:
        if _resolved(t["cfg"]) == _resolved(cfg):
            return t["result"]  # already measured this round
    # pin EVERY knob explicitly: an unset env var would fall back to a
    # stale TUNED.json inside the bench child, mislabeling the trial
    env = dict(os.environ,
               _PT_BENCH_GUARDED="1",  # we are the watchdog
               PT_BENCH_SKIP_VALIDATE="1",
               PT_BENCH_BATCH=str(cfg["batch"]),
               PT_BENCH_SEQ=str(cfg["seq"]),
               PT_BENCH_REMAT=str(cfg["remat"]).lower(),
               PT_FLASH_BLOCK_Q=str(cfg.get("block_q")
                                    or _TD.DEFAULT_FLASH_BLOCK_Q),
               PT_FLASH_BLOCK_K=str(cfg.get("block_k")
                                    or _TD.DEFAULT_FLASH_BLOCK_K),
               PT_BENCH_NMICRO=str(cfg.get("n_micro", 0)),
               PT_FUSED_CE="1" if cfg.get("fused_ce") else "0")
    t0 = time.perf_counter()
    try:
        r = subprocess.run([sys.executable, CHILD],
                           env=env, capture_output=True, text=True,
                           timeout=TRIAL_TIMEOUT)
    except subprocess.TimeoutExpired:
        print(f"  trial {cfg} TIMED OUT after {TRIAL_TIMEOUT}s", flush=True)
        trials.append({"cfg": cfg, "result": None, "error": "timeout"})
        return None
    out = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):  # bare numbers/strings are valid JSON
            out = parsed
            break
    if r.returncode != 0 or out is None:
        tail = "\n".join(r.stderr.strip().splitlines()[-4:])
        print(f"  trial {cfg} FAILED rc={r.returncode}: {tail}", flush=True)
        trials.append({"cfg": cfg, "result": None,
                       "error": f"rc={r.returncode}"})
        return None
    if out.get("extra", {}).get("backend") == "cpu":
        # tunnel died mid-search and the bench child fell back to the
        # CPU smoke — a number that must never reach TUNED.json
        print(f"  trial {cfg} INVALID: child fell back to CPU", flush=True)
        trials.append({"cfg": cfg, "result": None, "error": "cpu_fallback"})
        return None
    if out.get("extra", {}).get("pallas_fallback"):
        # Mosaic rejected this block config and bench.py silently
        # re-ran on the XLA attention path — scoring that number as
        # this pallas config would poison TUNED.json
        print(f"  trial {cfg} INVALID: pallas rejected, XLA fallback ran",
              flush=True)
        trials.append({"cfg": cfg, "result": None,
                       "error": "pallas_fallback"})
        return None
    dt = time.perf_counter() - t0
    print(f"  trial {cfg}: {out['value']} tok/s "
          f"(mfu={out['extra']['mfu']}, {dt:.0f}s wall)", flush=True)
    trials.append({"cfg": cfg, "result": out})
    return out


def score(res):
    return res["value"] if res else -1.0


def persist(best_cfg, best_res, trials, done):
    data = {"best": dict(best_cfg, tok_s=best_res["value"],
                         mfu=best_res["extra"]["mfu"],
                         mfu_legacy=best_res["extra"].get("mfu_legacy")),
            "stages_done": done, "n_trials": len(trials), "smoke": SMOKE,
            "trials": [{"cfg": t["cfg"],
                        "tok_s": t["result"]["value"] if t["result"] else None,
                        "error": t.get("error")} for t in trials],
            "ts": time.time()}
    tmp = TUNED + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, TUNED)
    print(f"{os.path.basename(TUNED)} <- {data['best']}", flush=True)


def main():
    if SMOKE:
        print(f"autotune: SMOKE mode (child={os.path.basename(CHILD)}, "
              f"out={os.path.basename(TUNED)})", flush=True)
    else:
        # refuse to tune on CPU — numbers would be meaningless as defaults
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=180)
            alive = probe.returncode == 0 and probe.stdout.strip() == "tpu"
        except subprocess.TimeoutExpired:
            alive = False  # half-wedged tunnel: device init hung
        if not alive:
            print("autotune: TPU unreachable; not tuning", file=sys.stderr)
            sys.exit(1)

    seq = int(os.environ.get("PT_TUNE_SEQ", "2048"))
    trials = []
    best_cfg, best_res = None, None
    done = []

    def consider(cfg):
        nonlocal best_cfg, best_res
        res = run_trial(cfg, trials)
        if score(res) > score(best_res):
            best_cfg, best_res = cfg, res
            # persist on every improvement, not just stage boundaries —
            # a mid-stage tunnel death must not lose the search
            persist(best_cfg, best_res, trials, list(done))

    # stage A: batch x remat x fused_ce (remat=False OOM'd at batch 16
    # in r2 — only try it at the smallest batch). fused_ce avoids the
    # (B,S,V) logits materialization, so it both speeds the head and
    # frees HBM that may admit configs the plain head OOMs on.
    print("stage A: batch x remat x fused_ce", flush=True)
    for batch in (16, 24, 32):
        for remat in ("true", "dots"):
            for fce in (False, True):
                consider({"batch": batch, "seq": seq, "remat": remat,
                          "fused_ce": fce})
    for fce in (False, True):
        consider({"batch": 8, "seq": seq, "remat": "false",
                  "fused_ce": fce})
    if best_res is None:
        print("autotune: every stage-A trial failed; aborting",
              file=sys.stderr)
        sys.exit(1)
    done.append("A")
    persist(best_cfg, best_res, trials, done)

    # stage B: flash block sizes at the winner (must divide seq)
    print("stage B: flash block_q/block_k", flush=True)
    a_win = dict(best_cfg)
    for bq, bk in ((128, 128), (256, 256), (256, 512), (512, 256),
                   (512, 512)):
        consider(dict(a_win, block_q=bq, block_k=bk))
    done.append("B")
    persist(best_cfg, best_res, trials, done)

    # stage C: gradient accumulation (true grad-accum scan in
    # make_train_step — trades peak activation memory for a serial loop;
    # can unlock bigger batch or lighter remat)
    print("stage C: n_micro grad accumulation", flush=True)
    b_win = dict(best_cfg)
    for nm in (2, 4):
        if b_win["batch"] % nm == 0:
            consider(dict(b_win, n_micro=nm))
    done.append("C")
    persist(best_cfg, best_res, trials, done)
    print(json.dumps({"best": best_cfg, "tok_s": best_res["value"],
                      "mfu": best_res["extra"]["mfu"]}))


if __name__ == "__main__":
    main()
