"""TPU-native auto-tuner (parity: reference auto_tuner subsystem,
/root/reference/python/paddle/distributed/auto_tuner/tuner.py — a
parallel-config/batch search harness; ours searches the knobs that
matter on one TPU chip and persists the winner).

Staged search over (batch, remat policy, fused linear+CE head, flash
block_q/block_k, n_micro) for the headline Llama pretrain step:

  stage A: batch x remat x fused_ce coarse grid
  stage B: flash block sizes at the stage-A winner
  stage C: grad-accum microbatching at the stage-B winner

Every trial is a guarded `bench.py` child (so a Mosaic rejection or OOM
kills the trial, not the tuner) and appends to BENCH_HISTORY.jsonl via
bench.py's own history hook.  The winner is written to TUNED.json after
every stage (partial progress survives a mid-search tunnel death), and
bench.py reads TUNED.json as its defaults.

Run on a live chip:  python tools/autotune.py

Smoke mode (no hardware): PT_TUNE_SMOKE=1 skips the TPU-alive probe and
runs the full stage-A/B/C search against a stub child
(tools/_tune_smoke_child.py by default) that answers with deterministic
fake numbers — so the tuner's parsing, guards, dedup, and persistence
are all proven BEFORE its first unattended run on a real tunnel window.
Smoke results are written to TUNED.smoke.json (or $PT_TUNE_OUT), never
to the TUNED.json that bench.py reads as defaults.

Env knobs:
  PT_TUNE_SMOKE=1   — smoke mode (see above)
  PT_TUNE_CHILD     — path to the per-trial child script
  PT_TUNE_OUT       — output path override for the winner JSON
  PT_TUNE_TRIAL_TIMEOUT — per-trial wall clock (seconds)
  PT_TUNE_STAGES    — subset of "ABC" to run (default all): the capture
                      chain runs a stage-A-only pass early so a short
                      tunnel window still sweeps the big levers (batch x
                      remat x fused_ce) before the long-tail benches;
                      the later full pass re-measures cheaply off the
                      compile cache
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
SMOKE = os.environ.get("PT_TUNE_SMOKE") == "1"
# Smoke output must NEVER land on the TUNED.json bench.py reads as
# defaults — fake numbers as real defaults would poison the next
# on-chip bench.
TUNED = os.environ.get("PT_TUNE_OUT") or os.path.join(
    ROOT, "TUNED.smoke.json" if SMOKE else "TUNED.json")
_DEFAULT_CHILD = os.path.join(HERE, "_tune_smoke_child.py") if SMOKE \
    else os.path.join(ROOT, "bench.py")
CHILD = os.environ.get("PT_TUNE_CHILD") or _DEFAULT_CHILD

TRIAL_TIMEOUT = int(os.environ.get("PT_TUNE_TRIAL_TIMEOUT", "600"))

# circuit breaker: N consecutive tunnel-death-shaped trial failures
# (timeout or cpu_fallback) abort the search instead of burning
# TRIAL_TIMEOUT per remaining trial on a dead tunnel. Best-so-far is
# already persisted on every improvement.
DEAD_TRIP = int(os.environ.get("PT_TUNE_DEAD_TRIP", "3"))
_consec_dead = 0


class TunnelDead(RuntimeError):
    pass


def _tunnel_alive(timeout=180):
    """Run the shared canary (tools/_tpu_canary.py — uncached compile +
    random-value execute) in a child process; False when it hangs or
    fails. A child process because a dead tunnel hangs jax device
    init."""
    canary = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_tpu_canary.py")
    try:
        return subprocess.run([sys.executable, canary],
                              capture_output=True,
                              timeout=timeout).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _mark_trial(kind):
    """kind: 'ok' | 'dead' (timeout/cpu_fallback) | 'bad' (config)."""
    global _consec_dead
    _consec_dead = _consec_dead + 1 if kind == "dead" else 0
    if _consec_dead >= DEAD_TRIP:
        raise TunnelDead(
            f"{_consec_dead} consecutive timeout/cpu-fallback trials")
    if kind == "dead" and not SMOKE and not _tunnel_alive():
        # don't wait for DEAD_TRIP x TRIAL_TIMEOUT (2.25h at defaults):
        # a 3-minute canary right after a timed-out trial settles
        # whether the window died (2026-08-01: trial 2 of stage A hung
        # 45 min on a tunnel that died after trial 1)
        raise TunnelDead("post-trial canary failed (window died)")


def _load_defaults():
    import importlib.util
    p = os.path.join(ROOT, "paddle_tpu", "_tuning_defaults.py")
    spec = importlib.util.spec_from_file_location("_tuning_defaults", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_TD = _load_defaults()

# Stage A: batch x remat x fused_ce, ordered by expected win so a short
# tunnel window still measures the promising region first. 2026-08-01
# on-chip evidence (first honest pass): full-remat MFU CLIMBS with
# batch — 16→0.33, 24→0.43, 32→0.60 strict — while dots at batch 8
# disappointed (0.22). So the big-batch full-remat ladder leads, pushed
# to the OOM wall (48/64), with dots as the secondary branch. fused_ce
# avoids the (B,S,V) logits materialization (speeds the head AND frees
# HBM); fused-off rungs ride along at every leading batch so the lever
# is quantified at whatever batch wins. The n_micro=2 corners exist
# because grad accumulation halves peak activation memory and may fit
# configs that OOM above — stage C only refines the winner, so those
# corners are never reached unless tried here. Module-level so the
# smoke tests derive trial counts instead of hardcoding them.
STAGE_A = [
    {"batch": 32, "remat": "true", "fused_ce": True},  # evidence leader
    {"batch": 48, "remat": "true", "fused_ce": True},
    {"batch": 64, "remat": "true", "fused_ce": True},
    {"batch": 32, "remat": "true", "fused_ce": False},
    {"batch": 48, "remat": "true", "fused_ce": False},
    {"batch": 64, "remat": "true", "fused_ce": False},
    {"batch": 24, "remat": "true", "fused_ce": True},
    {"batch": 40, "remat": "true", "fused_ce": True},
    {"batch": 16, "remat": "true", "fused_ce": True},
    {"batch": 32, "remat": "dots", "fused_ce": True},
    {"batch": 48, "remat": "dots", "fused_ce": True},
    {"batch": 16, "remat": "dots", "fused_ce": True},
    {"batch": 8, "remat": "dots", "fused_ce": True},
    {"batch": 16, "remat": "true", "fused_ce": False},
    {"batch": 64, "remat": "true", "fused_ce": True, "n_micro": 2},
    {"batch": 48, "remat": "dots", "fused_ce": True, "n_micro": 2},
    {"batch": 8, "remat": "false", "fused_ce": True},
]


def _resolved(cfg):
    """Dedup key over EFFECTIVE knobs: {batch,seq,remat} and the same
    cfg with explicit default block/n_micro values build identical
    child environments and must not be measured twice."""
    return (cfg["batch"], cfg["seq"], str(cfg["remat"]).lower(),
            bool(cfg.get("fused_ce"))) + _TD.effective_knobs(cfg)


def run_trial(cfg, trials):
    """One bench.py child at `cfg`; returns the parsed JSON line or None."""
    for t in trials:
        if t.get("prior"):
            # record carried over from an earlier staged pass for the
            # persisted trials log — not a full result (no extra),
            # never serve it as a measurement
            continue
        if _resolved(t["cfg"]) == _resolved(cfg):
            return t["result"]  # already measured this round
    # pin EVERY knob explicitly: an unset env var would fall back to a
    # stale TUNED.json inside the bench child, mislabeling the trial
    env = dict(os.environ,
               _PT_BENCH_GUARDED="1",  # we are the watchdog
               # a pallas-fallback number would be discarded below —
               # don't let the child burn trial time on the XLA retry
               PT_BENCH_NO_FALLBACK="1",
               PT_BENCH_SKIP_VALIDATE="1",
               PT_BENCH_BATCH=str(cfg["batch"]),
               PT_BENCH_SEQ=str(cfg["seq"]),
               PT_BENCH_REMAT=str(cfg["remat"]).lower(),
               PT_FLASH_BLOCK_Q=str(cfg.get("block_q")
                                    or _TD.DEFAULT_FLASH_BLOCK_Q),
               PT_FLASH_BLOCK_K=str(cfg.get("block_k")
                                    or _TD.DEFAULT_FLASH_BLOCK_K),
               PT_BENCH_NMICRO=str(cfg.get("n_micro", 0)),
               PT_FUSED_CE="1" if cfg.get("fused_ce") else "0")
    t0 = time.perf_counter()
    try:
        r = subprocess.run([sys.executable, CHILD],
                           env=env, capture_output=True, text=True,
                           timeout=TRIAL_TIMEOUT)
    except subprocess.TimeoutExpired:
        print(f"  trial {cfg} TIMED OUT after {TRIAL_TIMEOUT}s", flush=True)
        trials.append({"cfg": cfg, "result": None, "error": "timeout"})
        _mark_trial("dead")
        return None
    out = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):  # bare numbers/strings are valid JSON
            out = parsed
            break
    if r.returncode != 0 or out is None:
        tail = "\n".join(r.stderr.strip().splitlines()[-4:])
        print(f"  trial {cfg} FAILED rc={r.returncode}: {tail}", flush=True)
        trials.append({"cfg": cfg, "result": None,
                       "error": f"rc={r.returncode}"})
        _mark_trial("bad")
        return None
    if out.get("extra", {}).get("backend") == "cpu":
        # tunnel died mid-search and the bench child fell back to the
        # CPU smoke — a number that must never reach TUNED.json
        print(f"  trial {cfg} INVALID: child fell back to CPU", flush=True)
        trials.append({"cfg": cfg, "result": None, "error": "cpu_fallback"})
        _mark_trial("dead")
        return None
    if out.get("extra", {}).get("pallas_fallback"):
        # Mosaic rejected this block config and bench.py silently
        # re-ran on the XLA attention path — scoring that number as
        # this pallas config would poison TUNED.json
        print(f"  trial {cfg} INVALID: pallas rejected, XLA fallback ran",
              flush=True)
        trials.append({"cfg": cfg, "result": None,
                       "error": "pallas_fallback"})
        _mark_trial("bad")
        return None
    dt = time.perf_counter() - t0
    print(f"  trial {cfg}: {out['value']} tok/s "
          f"(mfu={out['extra']['mfu']}, {dt:.0f}s wall)", flush=True)
    trials.append({"cfg": cfg, "result": out})
    _mark_trial("ok")
    return out


def score(res):
    return res["value"] if res else -1.0


def _tuned_defaults_for_refine():
    """(cfg, stages_done, prior_trials) recorded by a prior non-smoke
    search in this output file — lets PT_TUNE_STAGES=BC refine an
    earlier stage-A pass without re-running it. Requires stage A to
    have actually COMPLETED: a best persisted mid-stage-A (timeout kill
    between consider() and done.append) must not let the refine pass
    mark the search finished with most of the grid unsearched."""
    try:
        with open(TUNED) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None, [], []
    if data.get("smoke") or "best" not in data \
            or "A" not in data.get("stages_done", []):
        return None, [], []
    # PT_TUNE_MIN_TS (set by tpu_capture.sh to its own start time)
    # rejects a stale winner from a previous window: if THIS window's
    # stage-A pass banked nothing, refining last week's best would
    # stamp the search complete without the grid ever being swept today
    min_ts = float(os.environ.get("PT_TUNE_MIN_TS", "0") or 0)
    if data.get("ts", 0) < min_ts:
        print(f"autotune: recorded best is older than PT_TUNE_MIN_TS "
              f"({data.get('ts')} < {min_ts}); not refining it",
              file=sys.stderr)
        return None, [], []
    cfg = {k: v for k, v in data["best"].items()
           if k not in ("tok_s", "mfu", "mfu_legacy")}
    prior = [{"cfg": t["cfg"], "prior": True,
              "result": ({"value": t["tok_s"]} if t.get("tok_s") is not None
                         else None),
              "error": t.get("error")}
             for t in data.get("trials", [])]
    return cfg, list(data.get("stages_done", [])), prior


def _merge_tuned(updates):
    """Atomically merge top-level keys into TUNED.json, preserving
    whatever other stages wrote there."""
    data = {}
    try:
        with open(TUNED) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    data.update(updates)
    tmp = TUNED + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, TUNED)
    return data


def persist(best_cfg, best_res, trials, done):
    data = _merge_tuned(dict(
        best=dict(best_cfg, tok_s=best_res["value"],
                  mfu=best_res["extra"]["mfu"],
                  mfu_legacy=best_res["extra"].get("mfu_legacy")),
        stages_done=done, n_trials=len(trials), smoke=SMOKE,
        # refresh provenance: _merge_tuned preserves unknown keys, so
        # a hand-seeded "source" note from a previous window would
        # otherwise survive and describe the WRONG measurement
        source=(f"autotune search on this host (stages "
                f"{','.join(done) or 'in-progress'}, "
                f"{len(trials)} trials); best re-measured fresh, "
                "not hand-seeded"),
        trials=[dict({"cfg": t["cfg"],
                      "tok_s": t["result"]["value"] if t["result"] else None,
                      "error": t.get("error")},
                     **({"prior": True} if t.get("prior") else {}))
                for t in trials],
        ts=time.time()))
    print(f"{os.path.basename(TUNED)} <- {data['best']}", flush=True)


# ---------------------------------------------------------------------------
# stage D: parallel-config search on the virtual CPU mesh (reference
# parity: the auto_tuner's dp/tp/pp/sharding search with cost-model
# pruning, /root/reference/python/paddle/distributed/auto_tuner/
# {search,prune,cost_model}.py). Needs NO hardware: each candidate is
# timed on the 8-device CPU mesh (captures partition imbalance and
# schedule bubbles) and scored with an analytic ICI comm model
# (captures what CPU timing cannot — the collectives' on-chip cost).
# ---------------------------------------------------------------------------
# stage-D child model dims per PT_TUNE_PAR_SIZE — enumeration, the comm
# cost model, and the compute estimate must all use the dims the child
# actually runs, or the ranking scores a model that was never measured
PAR_MODELS = {
    "small": {"hidden": 256, "layers": 8, "ffn": 704, "vocab": 1024,
              "batch": 8, "seq": 128, "heads": 8},
    "tiny": {"hidden": 64, "layers": 8, "ffn": 128, "vocab": 128,
             "batch": 8, "seq": 32, "heads": 4},
}
PAR_MODEL = PAR_MODELS["small"]
V5E_ICI_BPS = 1.6e11   # ~per-chip ICI bandwidth, bytes/s (order-of-mag)
V5E_FLOPS = 197e12 * 0.4  # assume 40% MFU for the compute-time estimate


def model_flops(model):
    """fwd+bwd matmul FLOPs per step of the stage-D child model (6N
    convention, lm_head kept) — single source for the bubble term and
    the score's compute estimate."""
    H, L, F_, V = (model["hidden"], model["layers"], model["ffn"],
                   model["vocab"])
    return 6 * (L * (4 * H * H + 3 * H * F_) + V * H) \
        * model["batch"] * model["seq"]


def enumerate_parallel_configs(n_devices, n_layers, batch, n_heads):
    """Candidate placements with reference-style pruning
    (auto_tuner/prune.py parity): device/layer/batch/head divisibility,
    tp capped at head count; pp adds n_micro x {1f1b, interleave}
    (interleave only when layers admit 2 chunks per stage); ZeRO-3 only
    for the pure-dp placement."""
    out = []
    for pp in (1, 2, 4, 8):
        for tp in (1, 2, 4, 8):
            if pp * tp > n_devices or n_devices % (pp * tp):
                continue
            dp = n_devices // (pp * tp)
            if n_layers % pp or batch % dp or n_heads % tp:
                continue
            base = {"dp": dp, "tp": tp, "pp": pp, "fused_ce": True}
            if pp == 1:
                out.append(dict(base))
                if tp == 1 and dp > 1:
                    out.append(dict(base, zero=True))
                continue
            for nm in (2, 4):
                if batch % nm:
                    continue
                out.append(dict(base, n_micro=nm, schedule="1f1b"))
                if n_layers % (pp * 2) == 0:
                    out.append(dict(base, n_micro=nm,
                                    schedule="interleave", vpp=2))
    return out


def parallel_comm_cost(cfg, model=PAR_MODEL):
    """Analytic per-step ICI seconds for a placement (bf16 wire bytes).

    tp: 4 activation all-reduces per layer (2 fwd + 2 bwd, megatron);
    dp: one grad all-reduce (2x param bytes ring cost);
    zero: + param all-gather fwd+bwd and reduce-scatter grads;
    pp: p2p activations per microbatch boundary, plus the schedule
    bubble inflating COMPUTE time (modeled on the compute estimate).
    A ranking heuristic to combine with measured CPU step time — not a
    simulator; calibrate against the chip when the tunnel returns.
    """
    H, L, F_, V = (model["hidden"], model["layers"], model["ffn"],
                   model["vocab"])
    B, S = model["batch"], model["seq"]
    dp, tp, pp = cfg.get("dp", 1), cfg.get("tp", 1), cfg.get("pp", 1)
    act = B * S * H * 2 / dp          # bf16 activation bytes per shard
    params = (L * (4 * H * H + 3 * H * F_) + 2 * V * H) * 2
    comm = 0.0
    if tp > 1:
        comm += 4 * L * act * (tp - 1) / tp / V5E_ICI_BPS
    if cfg.get("zero"):
        # ZeRO-3 REPLACES the grad all-reduce: param all-gather fwd +
        # bwd and grad reduce-scatter, ~3x param wire bytes total —
        # over the dp shard of THIS rank's tp/pp param slice, same
        # sharding the dp branch below charges
        comm += 3 * (params / (tp * pp)) * (dp - 1) / dp / V5E_ICI_BPS
    elif dp > 1:
        comm += 2 * (params / (tp * pp)) * (dp - 1) / dp / V5E_ICI_BPS
    if pp > 1:
        nm = cfg.get("n_micro", pp)
        comm += 2 * act * (pp - 1) / V5E_ICI_BPS  # p2p fwd+bwd
        compute = model_flops(model) / V5E_FLOPS
        fill = (pp - 1) / cfg.get("vpp", 1) if \
            cfg.get("schedule") == "interleave" else (pp - 1)
        comm += compute * fill / (nm + fill)      # bubble as lost time
    return comm


def run_parallel_trial(cfg, ndev=8, size="small", timeout=None):
    """One _tune_parallel_child.py run; returns step_time_s or None."""
    env = dict(os.environ, PT_TUNE_PAR_CFG=json.dumps(cfg),
               PT_TUNE_PAR_NDEV=str(ndev), PT_TUNE_PAR_SIZE=size)
    env.pop("JAX_PLATFORMS", None)  # child pins cpu via jax.config
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(HERE, "_tune_parallel_child.py")],
            env=env, capture_output=True, text=True,
            timeout=timeout or TRIAL_TIMEOUT)
    except subprocess.TimeoutExpired:
        print(f"  parallel trial {cfg} TIMED OUT", flush=True)
        return None
    out = None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict):
            out = parsed
            break
    if r.returncode != 0 or out is None:
        tail = "\n".join(r.stderr.strip().splitlines()[-3:])
        print(f"  parallel trial {cfg} FAILED rc={r.returncode}: {tail}",
              flush=True)
        return None
    return float(out["step_time_s"])


def run_parallel_search(ndev=8, size="small", runner=None, max_trials=None):
    """Measure every candidate, score = cpu_step_time x (1 + modeled
    ICI comm / modeled compute), prune dominated configs, and merge the
    ranking into TUNED.json under "parallel"."""
    model = PAR_MODELS[size]
    cands = enumerate_parallel_configs(ndev, model["layers"],
                                       model["batch"], model["heads"])
    if max_trials:
        cands = cands[:max_trials]
    runner = runner or (lambda cfg: run_parallel_trial(cfg, ndev, size))
    compute_s = model_flops(model) / V5E_FLOPS
    rows = []
    print(f"stage D: parallel placement search ({len(cands)} candidates, "
          f"{ndev} virtual devices)", flush=True)
    for cfg in cands:
        t = runner(cfg)
        if t is None:
            rows.append({"cfg": cfg, "step_time_s": None, "score": None})
            continue
        comm = parallel_comm_cost(cfg, model)
        score = t * (1.0 + comm / compute_s)
        rows.append({"cfg": cfg, "step_time_s": t,
                     "comm_model_s": round(comm, 6),
                     "score": round(score, 5)})
        print(f"  {cfg}: cpu {t:.3f}s, comm-model {comm * 1e3:.2f}ms, "
              f"score {score:.4f}", flush=True)
    ok = [r_ for r_ in rows if r_["score"] is not None]
    if not ok:
        print("stage D: every parallel trial failed", file=sys.stderr)
        return None
    ok.sort(key=lambda r_: r_["score"])
    # dominated = strictly worse on BOTH measured time and modeled comm
    for r_ in ok:
        r_["dominated"] = any(
            o is not r_ and o["step_time_s"] <= r_["step_time_s"]
            and o["comm_model_s"] <= r_["comm_model_s"]
            and (o["step_time_s"] < r_["step_time_s"]
                 or o["comm_model_s"] < r_["comm_model_s"])
            for o in ok)
    block = {"best": ok[0]["cfg"], "n_devices": ndev, "size": size,
             "model": model, "ranking": ok,
             "failed": [r_["cfg"] for r_ in rows if r_["score"] is None],
             "note": "cpu-mesh measured step time x analytic ICI comm "
                     "model; calibrate on chip", "ts": time.time()}
    _merge_tuned({"parallel": block})
    print(f"{os.path.basename(TUNED)} parallel <- {block['best']}",
          flush=True)
    return block


def main():
    if "--parallel" in sys.argv:
        # stage D runs WITHOUT hardware (virtual CPU mesh) — never
        # burn a tunnel window on it
        ok = run_parallel_search(
            ndev=int(os.environ.get("PT_TUNE_PAR_NDEV", "8")),
            size=os.environ.get("PT_TUNE_PAR_SIZE", "small"),
            max_trials=int(os.environ.get("PT_TUNE_PAR_MAX", "0")) or None)
        sys.exit(0 if ok else 1)
    if SMOKE:
        print(f"autotune: SMOKE mode (child={os.path.basename(CHILD)}, "
              f"out={os.path.basename(TUNED)})", flush=True)
    else:
        # refuse to tune on CPU — numbers would be meaningless as defaults
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=180)
            alive = probe.returncode == 0 and probe.stdout.strip() == "tpu"
        except subprocess.TimeoutExpired:
            alive = False  # half-wedged tunnel: device init hung
        if not alive:
            print("autotune: TPU unreachable; not tuning", file=sys.stderr)
            sys.exit(1)

    seq = int(os.environ.get("PT_TUNE_SEQ", "2048"))
    trials = []
    best_cfg, best_res = None, None
    done = []

    def consider(cfg):
        nonlocal best_cfg, best_res
        res = run_trial(cfg, trials)
        if score(res) > score(best_res):
            best_cfg, best_res = cfg, res
            # persist on every improvement, not just stage boundaries —
            # a mid-stage tunnel death must not lose the search
            persist(best_cfg, best_res, trials, list(done))

    stages = os.environ.get("PT_TUNE_STAGES", "ABC").upper()
    if not stages or not set(stages) <= set("ABC"):
        print(f"autotune: invalid PT_TUNE_STAGES={stages!r} "
              "(want a non-empty subset of 'ABC')", file=sys.stderr)
        sys.exit(2)
    try:
        if "A" in stages:
            print("stage A: batch x remat x fused_ce", flush=True)
            for cfg in STAGE_A:
                consider(dict(cfg, seq=seq))
            if best_res is None:
                print("autotune: every stage-A trial failed; aborting",
                      file=sys.stderr)
                sys.exit(1)
            done.append("A")
            persist(best_cfg, best_res, trials, done)
        else:
            # B/C refine the recorded stage-A winner from this window
            prev, prev_done, prior = _tuned_defaults_for_refine()
            if not prev:
                print("autotune: PT_TUNE_STAGES without A needs a prior "
                      "non-smoke TUNED.json with stage A completed",
                      file=sys.stderr)
                sys.exit(1)
            # keep earlier stages on the record, minus the ones this
            # pass re-runs (a BC refine over a full ABC file must not
            # persist ['A','B','C','B','C'])
            done.extend(s for s in prev_done if s not in stages)
            trials.extend(prior)     # and their trial log (marked prior)
            best_cfg = prev
            best_res = run_trial(dict(prev), trials)
            if best_res is None:
                print("autotune: could not re-measure the recorded best",
                      file=sys.stderr)
                sys.exit(1)

        if "B" in stages:
            # stage B: flash block sizes at the winner (must divide seq)
            print("stage B: flash block_q/block_k", flush=True)
            a_win = dict(best_cfg)
            for bq, bk in ((128, 128), (256, 256), (256, 512), (512, 256),
                           (512, 512)):
                consider(dict(a_win, block_q=bq, block_k=bk))
            done.append("B")
            persist(best_cfg, best_res, trials, done)

        if "C" in stages:
            # stage C: gradient accumulation (true grad-accum scan in
            # make_train_step — trades peak activation memory for a
            # serial loop; can unlock bigger batch or lighter remat)
            print("stage C: n_micro grad accumulation", flush=True)
            b_win = dict(best_cfg)
            for nm in (2, 4):
                if b_win["batch"] % nm == 0:
                    consider(dict(b_win, n_micro=nm))
            done.append("C")
            persist(best_cfg, best_res, trials, done)
    except TunnelDead as e:
        print(f"autotune: aborting search — {e}; "
              f"stages completed: {done or 'none'}", file=sys.stderr)
        if best_res is None:
            sys.exit(3)
        # re-persist so the trials record includes the dead trials that
        # tripped the breaker — TUNED.json must explain why the search
        # stopped, not just stderr
        persist(best_cfg, best_res, trials, list(done))
    print(json.dumps({"best": best_cfg, "tok_s": best_res["value"],
                      "mfu": best_res["extra"]["mfu"]}))


if __name__ == "__main__":
    main()
