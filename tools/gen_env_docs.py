#!/usr/bin/env python
"""Generate docs/env.md from the paddle_tpu._env knob registry.

Pure stdlib: loads paddle_tpu/_env.py as a standalone module (no jax,
no paddle_tpu package import) so doc generation runs on any box.

Usage:
    python tools/gen_env_docs.py            # rewrite docs/env.md
    python tools/gen_env_docs.py --check    # exit 1 when out of sync

The tier-1 selfcheck runs --check, so a knob added to _env.py without
regenerating the table fails CI with a one-command fix.
"""
from __future__ import annotations

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV_PY = os.path.join(REPO, "paddle_tpu", "_env.py")
DOC = os.path.join(REPO, "docs", "env.md")

_SECTION_TITLES = {
    "serving": "Serving runtime",
    "slo": "SLO classes",
    "pulse": "Pulse / anomaly capture",
    "fleet": "Fleet plane",
    "observability": "Observability",
    "kernels": "Kernels",
    "distributed": "Distributed / RPC",
    "io": "Data / checkpoint IO",
    "general": "General",
}


def _load_env_module():
    spec = importlib.util.spec_from_file_location("_pt_env_docgen", ENV_PY)
    module = importlib.util.module_from_spec(spec)
    # dataclasses resolve cls.__module__ through sys.modules during
    # class creation — the module MUST be registered before exec.
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def _default_cell(knob):
    if knob.default is None:
        return "_(unset)_"
    if knob.default == "":
        return '`""`'
    return f"`{knob.default}`"


def render():
    env = _load_env_module()
    by_section = {}
    for k in env.knobs():
        by_section.setdefault(k.section, []).append(k)

    out = [
        "# Environment knobs",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Regenerate with: python tools/gen_env_docs.py -->",
        "",
        "Every `PT_*` / `PADDLE_TPU_*` environment variable the tree",
        "reads is declared in `paddle_tpu/_env.py` with a default and a",
        "one-line doc; tpulint rule TPL010 rejects undeclared reads, and",
        "the tier-1 selfcheck fails when this table drifts from the",
        "registry. Names ending in `*` are patterns: a family of knobs",
        "(for example one per SLO class) sharing one parser and doc.",
        "",
    ]
    for section in sorted(by_section):
        title = _SECTION_TITLES.get(section, section.title())
        out.append(f"## {title}")
        out.append("")
        out.append("| Name | Default | Kind | What it does |")
        out.append("|---|---|---|---|")
        for k in by_section[section]:
            out.append(f"| `{k.name}` | {_default_cell(k)} "
                       f"| {k.kind} | {k.doc} |")
        out.append("")
    return "\n".join(out).rstrip() + "\n"


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    check = "--check" in argv
    text = render()
    current = ""
    if os.path.exists(DOC):
        with open(DOC, "r", encoding="utf-8") as f:
            current = f.read()
    if check:
        if current != text:
            sys.stderr.write(
                "docs/env.md is out of sync with paddle_tpu/_env.py — "
                "run: python tools/gen_env_docs.py\n")
            return 1
        return 0
    if current != text:
        with open(DOC, "w", encoding="utf-8") as f:
            f.write(text)
        print(f"wrote {os.path.relpath(DOC, REPO)}")
    else:
        print("docs/env.md already in sync")
    return 0


if __name__ == "__main__":
    sys.exit(main())
