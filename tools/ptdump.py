#!/usr/bin/env python3
"""ptdump — pretty-print paddle_tpu observability dumps.

Accepts either artifact the runtime produces and figures out which it
got:

  * a flight-recorder dump (`/debug/flightrecorder`, SIGTERM, or
    `flight_recorder.dump()`): prints the header, per-kind event
    counts, compile telemetry rollup, and the tail of the ring;
  * a chrome-tracing export (`/debug/trace`, `Profiler.export`, or an
    `export_chrome_tracing` handler file): prints per-span aggregates
    and per-trace (request) timelines;
  * a pulse capture bundle (the directory the pulse plane writes on a
    stall/restart/breaker/SLO-burst trigger): stitches meta, the
    triggering pulse window, the recent-request ring, and the flight
    dump into one post-mortem narrative;
  * a FLEET capture bundle (per-host subdirectories written by rank 0
    on a worker trigger): the same narrative across every process —
    trigger, triggering trace ids, clock offsets, then each
    replica@host section's requests and flight tail.

Pure stdlib — runs anywhere, no jax needed.

  python tools/ptdump.py /tmp/pt_flightrecorder-1234.json
  python tools/ptdump.py trace.json --tail 50 --kind compile
  python tools/ptdump.py bundle /tmp/pt_captures/bundle-...-step_stall-1234
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _fmt_ts(ts):
    try:
        return time.strftime("%H:%M:%S", time.localtime(ts)) \
            + f".{int((ts % 1) * 1000):03d}"
    except Exception:
        return str(ts)


def _human_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def _human_flops(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(n) < 1000 or unit == "P":
            return f"{n:.3g}{unit}FLOP"
        n /= 1000.0


def _fmt_fields(ev, skip=("kind", "ts", "seq")):
    parts = []
    for k, v in ev.items():
        if k in skip:
            continue
        # device-telemetry records carry raw counts; humanize them
        if v is not None and (k.endswith("_bytes") or k == "bytes"):
            v = _human_bytes(v)
        elif v is not None and (k == "flops" or k.endswith("_flops")):
            v = _human_flops(v)
        elif isinstance(v, float):
            v = f"{v:.6g}"
        parts.append(f"{k}={v}")
    return " ".join(parts)


# ---------------------------------------------------------------------------
# flight-recorder dumps
# ---------------------------------------------------------------------------
def print_flight(doc, tail=30, kind=None, out=sys.stdout):
    w = out.write
    w(f"flight recorder dump — pid {doc.get('pid')} "
      f"at {_fmt_ts(doc.get('dumped_at', 0))} "
      f"(reason: {doc.get('reason', '?')})\n")
    w(f"  ring: {len(doc.get('events', []))} events held, "
      f"{doc.get('dropped', 0)} rotated out, "
      f"capacity {doc.get('capacity')}\n")
    comp = doc.get("compile") or {}
    if comp:
        w(f"  compile: {comp.get('compiles', 0)} compiles, "
          f"{comp.get('retraces', 0)} retraces, "
          f"{comp.get('compile_seconds', 0):.3f}s across "
          f"{comp.get('functions', 0)} functions\n")
    events = doc.get("events", [])
    by_kind = {}
    for e in events:
        by_kind[e.get("kind", "?")] = by_kind.get(e.get("kind", "?"), 0) + 1
    w("  by kind: " + ", ".join(f"{k}={n}" for k, n in
                                sorted(by_kind.items())) + "\n")
    # device-telemetry rollups: latest memory snapshot, per-fn XLA
    # costs, health incidents — the records PR 4's accountant/cost
    # registry/monitor leave in the ring
    mems = [e for e in events if e.get("kind") == "device.memory"]
    if mems:
        m = mems[-1]
        w(f"  device memory (latest of {len(mems)}): "
          f"live={_human_bytes(m.get('live_bytes'))} "
          f"in {m.get('live_arrays')} arrays, "
          f"peak={_human_bytes(m.get('live_peak_bytes'))}")
        if m.get("bytes_in_use") is not None:
            w(f", allocator={_human_bytes(m['bytes_in_use'])}"
              f"/{_human_bytes(m.get('bytes_limit'))}")
        w("\n")
    costs = {}
    for e in events:
        if e.get("kind") == "device.cost":
            costs[e.get("fn", "?")] = e      # latest signature wins
    for fn, e in sorted(costs.items()):
        hbm = sum(e.get(k) or 0 for k in
                  ("argument_bytes", "output_bytes", "temp_bytes"))
        w(f"  cost {fn}: {_human_flops(e.get('flops'))}"
          f" {_human_bytes(e.get('bytes_accessed'))} accessed,"
          f" hbm {_human_bytes(hbm)}\n")
    # KV tiering rollup: spill/hit traffic through the host-RAM tier
    # (kvtier.spill carries the landed page's bytes + the ledger it
    # left behind; kvtier.hit the pages restored to the device)
    spills = [e for e in events if e.get("kind") == "kvtier.spill"]
    thits = [e for e in events if e.get("kind") == "kvtier.hit"]
    if spills or thits:
        sp_bytes = sum(e.get("bytes") or 0 for e in spills)
        re_pages = sum(e.get("pages") or 0 for e in thits)
        re_tokens = sum(e.get("tokens") or 0 for e in thits)
        w(f"  kv tier: {len(spills)} spills "
          f"({_human_bytes(sp_bytes)} demoted), {len(thits)} hits "
          f"({re_pages} pages / {re_tokens} tokens restored)")
        if spills:
            last = spills[-1]
            w(f"; holding {_human_bytes(last.get('tier_bytes'))} "
              f"in {last.get('tier_pages')} pages")
        w("\n")
    # disaggregated handoff rollup: handoff.export/import carry each
    # migrated request's KV payload size; handoff.fail the degraded
    # ones (export fail -> local decode, import fail -> recompute)
    hexp = [e for e in events if e.get("kind") == "handoff.export"]
    himp = [e for e in events if e.get("kind") == "handoff.import"]
    hfail = [e for e in events if e.get("kind") == "handoff.fail"]
    if hexp or himp or hfail:
        ex_bytes = sum(e.get("bytes") or 0 for e in hexp)
        im_pages = sum(e.get("pages") or 0 for e in himp)
        w(f"  kv handoff: {len(hexp)} exports "
          f"({_human_bytes(ex_bytes)} shipped), {len(himp)} imports "
          f"({im_pages} pages landed)")
        if hfail:
            wh = {}
            for e in hfail:
                wh[e.get("where", "?")] = wh.get(e.get("where", "?"),
                                                 0) + 1
            w(f", {len(hfail)} degraded "
              f"({', '.join(f'{k}:{v}' for k, v in sorted(wh.items()))})")
        w("\n")
    # crash-recovery rollup: engine.restart records carry what each
    # warm restart did (requeued / failed / quarantined, and whether
    # the crash-loop breaker tripped); poison.quarantine and
    # fault.injected events tell the drill's story alongside
    restarts = [e for e in events if e.get("kind") == "engine.restart"]
    if restarts:
        req = sum(e.get("requeued") or 0 for e in restarts)
        fail = sum(e.get("failed") or 0 for e in restarts)
        quar = sum(e.get("quarantined") or 0 for e in restarts)
        inj = sum(1 for e in events if e.get("kind") == "fault.injected")
        w(f"  engine restarts: {len(restarts)} "
          f"({req} requeued, {fail} failed, {quar} quarantined")
        if inj:
            w(f", {inj} injected faults")
        w(")")
        if any(e.get("broken") for e in restarts):
            last = [e for e in restarts if e.get("broken")][-1]
            w(f"; crash-loop breaker OPEN "
              f"(last error {last.get('error')})")
        w("\n")
    # step-loop rollup: the rate-limited serving.step records carry the
    # pump's wall time, the host gap between device-step launches, and
    # the pipeline depth (1 = double-buffered pump) — enough to read
    # "was the host on the critical path" straight off a flight dump
    steps = [e for e in events if e.get("kind") == "serving.step"]
    if steps:
        n = len(steps)
        tot = sum(e.get("step_s") or 0.0 for e in steps)
        gaps = [e.get("host_gap_s") for e in steps
                if e.get("host_gap_s") is not None]
        depth = max((e.get("pipeline_depth") or 0) for e in steps)
        w(f"  serving steps: {n} sampled, "
          f"avg step {tot / n * 1e3:.2f}ms")
        if gaps:
            w(f", avg host gap {sum(gaps) / len(gaps) * 1e6:.0f}us")
        w(f", pipeline depth {int(depth)}\n")
    # request-timeline rollup: request.done records with a `phases`
    # breakdown (the stitched per-request ledger) — per-phase p50/p99,
    # the SLO violations attributed to each phase, and the slowest
    # requests end-to-end with where their time went
    dones = [e for e in events
             if e.get("kind") == "request.done" and e.get("phases")]
    if dones:
        w(f"  request timelines: {len(dones)} completed\n")
        by_phase = {}
        for e in dones:
            for ph, s in (e.get("phases") or {}).items():
                by_phase.setdefault(ph, []).append(float(s))
        w(f"    {'phase':<12}{'n':>6}{'p50_ms':>10}{'p99_ms':>10}\n")
        for ph, xs in sorted(by_phase.items()):
            xs.sort()
            p50 = xs[int(0.50 * (len(xs) - 1))]
            p99 = xs[int(0.99 * (len(xs) - 1))]
            w(f"    {ph:<12}{len(xs):>6}{p50 * 1e3:>10.2f}"
              f"{p99 * 1e3:>10.2f}\n")
        viols = {}
        for e in dones:
            if e.get("slo_attained") is False:
                ph = e.get("violated_phase") or "?"
                viols[ph] = viols.get(ph, 0) + 1
        if viols:
            w("    slo violations by phase: "
              + ", ".join(f"{k}={n}" for k, n in sorted(viols.items()))
              + "\n")
        slow = sorted(dones, key=lambda e: -(e.get("e2e_s") or 0.0))[:5]
        w("    slowest:\n")
        for e in slow:
            br = " ".join(f"{k}={v * 1e3:.1f}ms" for k, v in
                          sorted((e.get("phases") or {}).items()) if v)
            w(f"      {e.get('rid')}: {(e.get('e2e_s') or 0) * 1e3:.1f}ms"
              f" ({e.get('tokens')} tok) {br}\n")
    anoms = [e for e in events if e.get("kind") == "anomaly.step_stall"]
    if anoms:
        last = anoms[-1]
        w(f"  step anomalies: {len(anoms)} flagged; last "
          f"{(last.get('step_s') or 0) * 1e3:.1f}ms vs baseline "
          f"{(last.get('mean_s') or 0) * 1e3:.1f}ms "
          f"(threshold {(last.get('threshold_s') or 0) * 1e3:.1f}ms)\n")
    health = [e for e in events if e.get("kind") == "health"]
    if health:
        bad = sum(e.get("count", 0) or 0 for e in health)
        blames = [e for e in health if e.get("event") == "nan_blame"]
        w(f"  health: {len(health)} incidents, {bad} non-finite values")
        if blames:
            w(f"; last blame: {blames[-1].get('layer')}")
        w("\n")
    if kind:
        events = [e for e in events if e.get("kind") == kind]
        w(f"  filtered kind={kind}: {len(events)} events\n")
    w(f"--- last {min(tail, len(events))} events ---\n")
    for e in events[-tail:]:
        w(f"{_fmt_ts(e.get('ts', 0))} [{e.get('kind', '?'):>8}] "
          f"{_fmt_fields(e)}\n")


# ---------------------------------------------------------------------------
# pulse capture bundles
# ---------------------------------------------------------------------------
def _load_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def print_fleet_bundle(path, tail=30, kind=None, out=sys.stdout):
    """Cross-host post-mortem narrative for one FLEET capture bundle
    (rank 0 pulled every worker's evidence on a pulse trigger): the
    trigger + triggering trace ids, the clock offsets used to line the
    hosts up, then one section per process — router first, each
    replica@host after — with its request ring and flight tail."""
    w = out.write
    meta = _load_json(os.path.join(path, "meta.json")) or {}
    w(f"fleet capture bundle — "
      f"{os.path.basename(os.path.abspath(path))}\n")
    w(f"  trigger: {meta.get('trigger', '?')} "
      f"(reported by {meta.get('worker', '?')}) "
      f"at {_fmt_ts(meta.get('at', 0))} "
      f"(router pid {meta.get('pid')})\n")
    tids = meta.get("trace_ids") or []
    if tids:
        w(f"  triggering traces: {', '.join(str(t) for t in tids)}\n")
    sections = meta.get("sections") or []
    if sections:
        w(f"  fleet clock ({len(sections)} processes, offset = how "
          f"far that clock runs ahead of the router's):\n")
        for s in sections:
            w(f"    {s.get('label', '?'):<28} "
              f"offset={float(s.get('offset_s') or 0) * 1e3:+.3f}ms "
              f"(±{float(s.get('uncertainty_s') or 0) * 1e3:.3f}ms)\n")
    for s in sections:
        label = s.get("label", "?")
        sub = os.path.join(path, label)
        reqs = _load_json(os.path.join(sub, "requests.json")) or {}
        if isinstance(reqs, dict):
            reqs = reqs.get("requests") or []
        flight = _load_json(os.path.join(sub, "flight.json"))
        w(f"\n=== {label} ===\n")
        if reqs:
            w(f"  recent requests ({len(reqs)} in ring, newest "
              f"last):\n")
            for r in reqs[-min(6, len(reqs)):]:
                mark = " <- triggering" \
                    if r.get("trace_id") in tids else ""
                w(f"    {r.get('rid', '?')} "
                  f"trace={r.get('trace_id')} "
                  f"state={r.get('state', r.get('status', '?'))}"
                  f"{mark}\n")
        if flight:
            print_flight(flight, tail=tail, kind=kind, out=out)
        else:
            w("  (no flight.json in section)\n")


def print_bundle(path, tail=30, kind=None, out=sys.stdout):
    """Post-mortem narrative for one capture bundle directory: what
    fired, which requests were in flight, what the pulse rings saw
    around the trigger, then the flight-recorder tail. Fleet bundles
    (per-host subdirectories) dispatch to the cross-host printer."""
    w = out.write
    meta = _load_json(os.path.join(path, "meta.json")) or {}
    if meta.get("fleet"):
        print_fleet_bundle(path, tail=tail, kind=kind, out=out)
        return
    pulse = _load_json(os.path.join(path, "pulse.json")) or {}
    flight = _load_json(os.path.join(path, "flight.json"))
    reqs = _load_json(os.path.join(path, "requests.json")) or {}
    if isinstance(reqs, dict):
        reqs = reqs.get("requests") or []
    config = _load_json(os.path.join(path, "config.json")) or {}
    w(f"capture bundle — {os.path.basename(os.path.abspath(path))}\n")
    w(f"  trigger: {meta.get('trigger', '?')} "
      f"at {_fmt_ts(meta.get('at', 0))} (pid {meta.get('pid')})\n")
    tids = meta.get("trace_ids") or []
    if tids:
        w(f"  in-flight traces: {', '.join(str(t) for t in tids)}\n")
    info = meta.get("info") or {}
    if info:
        w("  scheduler: " + " ".join(
            f"{k}={info[k]}" for k in sorted(info)
            if k != "trace_ids") + "\n")
    totals = {k: n for k, n in
              (meta.get("triggers_total") or {}).items() if n}
    if totals:
        w("  triggers so far: " + ", ".join(
            f"{k}={n}" for k, n in sorted(totals.items())) + "\n")
    sigs = pulse.get("signals") or {}
    if sigs:
        w(f"  pulse window: {len(sigs)} signals; notable:\n")
        notable = [n for n in sorted(sigs)
                   if ("step_seconds" in n or "anomal" in n
                       or "restart" in n or "violated" in n
                       or "queue_depth" in n or n == "goodput_ratio")]
        for name in notable[:12]:
            series = sigs[name] or []
            if not series:
                continue
            vals = [v for _, v in series]
            w(f"    {name:<44} last={vals[-1]:.6g} "
              f"min={min(vals):.6g} max={max(vals):.6g} "
              f"n={len(vals)}\n")
    if reqs:
        w(f"  recent requests ({len(reqs)} in ring, newest last):\n")
        for r in reqs[-min(8, len(reqs)):]:
            mark = " <- triggering" if r.get("trace_id") in tids else ""
            w(f"    {r.get('rid', '?')} trace={r.get('trace_id')} "
              f"state={r.get('state', r.get('status', '?'))}{mark}\n")
    argv = (config.get("env") or {}).get("argv") or config.get("argv")
    if argv:
        w(f"  process: {' '.join(map(str, argv))}\n")
    if flight:
        w("\n")
        print_flight(flight, tail=tail, kind=kind, out=out)
    else:
        w("  (no flight.json in bundle)\n")


# ---------------------------------------------------------------------------
# chrome traces
# ---------------------------------------------------------------------------
def print_chrome(doc, tail=30, out=sys.stdout):
    w = out.write
    evs = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    w(f"chrome trace — {len(evs)} complete events\n")
    if not evs:
        return
    t0 = min(e["ts"] for e in evs)
    t1 = max(e["ts"] + e.get("dur", 0) for e in evs)
    w(f"  wall span: {(t1 - t0) / 1e3:.3f} ms\n")
    agg = {}
    for e in evs:
        tot, cnt = agg.get(e["name"], (0.0, 0))
        agg[e["name"]] = (tot + e.get("dur", 0), cnt + 1)
    w(f"--- by span name ---\n")
    w(f"{'span':<36}{'calls':>8}{'total_ms':>12}{'avg_us':>12}\n")
    for name, (tot, cnt) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        w(f"{name:<36}{cnt:>8}{tot / 1e3:>12.3f}{tot / cnt:>12.1f}\n")
    traces = {}
    for e in evs:
        tid = (e.get("args") or {}).get("trace_id")
        if tid is not None:
            traces.setdefault(tid, []).append(e)
    if traces:
        w(f"--- by trace id ({len(traces)} traces) ---\n")
        for tid, tevs in sorted(traces.items()):
            tevs.sort(key=lambda e: e["ts"])
            start = tevs[0]["ts"]
            end = max(e["ts"] + e.get("dur", 0) for e in tevs)
            w(f"{tid}: {len(tevs)} spans, {(end - start) / 1e3:.3f} ms\n")
            for e in tevs[:tail]:
                w(f"    +{(e['ts'] - start) / 1e3:>10.3f} ms "
                  f"{e['name']:<28} {e.get('dur', 0) / 1e3:.3f} ms\n")


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # `ptdump bundle <dir>` — the subcommand word is optional sugar;
    # a bare directory path dispatches to the bundle printer too
    if argv and argv[0] == "bundle":
        argv = argv[1:]
    ap = argparse.ArgumentParser(
        prog="ptdump", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="flight-recorder dump, chrome trace, "
                                 "or capture-bundle directory")
    ap.add_argument("--tail", type=int, default=30,
                    help="events/spans to show (default 30)")
    ap.add_argument("--kind", default=None,
                    help="flight dumps: only this event kind")
    args = ap.parse_args(argv)
    if os.path.isdir(args.path):
        print_bundle(args.path, tail=args.tail, kind=args.kind)
        return 0
    with open(args.path) as f:
        doc = json.load(f)
    if "traceEvents" in doc:
        print_chrome(doc, tail=args.tail)
    elif "events" in doc:
        print_flight(doc, tail=args.tail, kind=args.kind)
    else:
        sys.stderr.write(
            "ptdump: unrecognized document (want a flight-recorder "
            "dump with 'events' or a chrome trace with 'traceEvents')\n")
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
