#!/usr/bin/env python3
"""ptop — terminal dashboard over the serving pulse plane.

Renders `GET /debug/pulse` (docs/observability.md § Pulse & capture
bundles) as one sparkline row per signal — counter rates, gauge
samples, windowed histogram percentiles — with per-replica columns
when a Router is mounted, and stall/violation signals highlighted the
moment they go non-zero. Three modes:

  python tools/ptop.py http://HOST:PORT              # poll + redraw
  python tools/ptop.py http://HOST:PORT --stream     # SSE live feed
  python tools/ptop.py --file pulse.json --once      # recorded payload

Pure stdlib — runs anywhere, no jax needed. `--once` renders a single
frame and exits (how tests drive it deterministically).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

BARS = "▁▂▃▄▅▆▇█"

# signals that should scream when non-zero: stalls, SLO violations,
# restarts/breaker, requeues, failures
_HOT = ("anomal", "violated", "restart", "breaker", "requeue",
        "fail", "poison", "reject")

_RED = "\x1b[31m"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RESET = "\x1b[0m"


def sparkline(values, width=24):
    """Unicode sparkline of the LAST `width` values, min-max
    normalized (flat series render as a low bar)."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return " " * width
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return (BARS[0] * len(vals)).rjust(width)
    idx = [min(int((v - lo) / span * (len(BARS) - 1) + 0.5),
               len(BARS) - 1) for v in vals]
    return "".join(BARS[i] for i in idx).rjust(width)


def _human_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def _fmt_value(name, v):
    try:
        v = float(v)
    except (TypeError, ValueError):
        return str(v)
    if "_seconds" in name or name.endswith((":p50", ":p99")):
        return f"{v * 1e3:.2f}ms"
    # byte counters (pt_wire_{tx,rx}_bytes, spill/handoff bytes) read
    # better humanized — as a rate when the pulse plane derived one
    if "_bytes" in name:
        h = _human_bytes(v)
        return f"{h}/s" if name.endswith(":rate") else h
    if name.endswith(":rate"):
        return f"{v:.2f}/s"
    if v == int(v) and abs(v) < 1e9:
        return str(int(v))
    return f"{v:.4g}"


def _is_hot(name, series):
    return any(tok in name for tok in _HOT) and \
        any(v > 0 for _, v in series)


def _paint(text, code, color):
    return f"{code}{text}{_RESET}" if color else text


def render(payload, out=sys.stdout, width=24, color=False):
    """One frame. Accepts the flat single-scheduler payload or the
    router's `{"replicas": {rid: payload}}` aggregate — the latter
    renders per-replica columns for every signal."""
    w = out.write
    if not payload.get("enabled", False):
        w("pulse plane disabled (PT_SERVE_PULSE=0 or no data)\n")
        return
    reps = payload.get("replicas")
    if reps is None:
        reps = {"": payload}
    cols = sorted(reps)
    header = f"ptop — {time.strftime('%H:%M:%S')}"
    first = next(iter(reps.values()), {})
    if first.get("interval_s"):
        header += f"  interval {first['interval_s']:g}s"
    trig = {}
    bundles = []
    for p in reps.values():
        for k, n in (p.get("triggers") or {}).items():
            trig[k] = trig.get(k, 0) + n
        bundles.extend(p.get("bundles") or [])
    fired = {k: n for k, n in trig.items() if n}
    if fired:
        header += "  triggers " + ",".join(
            f"{k}={n}" for k, n in sorted(fired.items()))
    if bundles:
        header += f"  bundles {len(bundles)}"
    w(_paint(header, _BOLD + (_RED if fired else ""), color) + "\n")
    if len(cols) > 1:
        cell = width + 12
        # fleet mode tags each replica payload with its host — show
        # `rid@host` so per-host aggregation is readable at a glance
        heads = [f"{c}@{reps[c]['host']}" if reps[c].get("host") else c
                 for c in cols]
        w(" " * 44 + "".join(
            _paint(f"{h[-cell:]:>{cell}}", _DIM, color)
            for h in heads) + "\n")
    names = sorted({n for p in reps.values()
                    for n in (p.get("signals") or {})})
    for name in names:
        cells = []
        hot = False
        for c in cols:
            series = (reps[c].get("signals") or {}).get(name) or []
            hot = hot or _is_hot(name, series)
            if not series:
                cells.append(" " * (width + 12))
                continue
            spark = sparkline([v for _, v in series], width)
            last = _fmt_value(name, series[-1][1])
            cells.append(f"{spark} {last:>11}")
        line = f"{name[:43]:<44}" + "".join(cells)
        w(_paint(line, _RED, color and hot) + "\n")
    if bundles:
        w(_paint("capture bundles:", _BOLD, color) + "\n")
        for b in bundles[-4:]:
            w(f"  {b}\n")


def fetch(url, window=None, signals=None, timeout=10.0):
    q = []
    if window is not None:
        q.append(f"window={int(window)}")
    if signals:
        q.append("signals=" + ",".join(signals))
    full = url.rstrip("/") + "/debug/pulse" + \
        ("?" + "&".join(q) if q else "")
    with urllib.request.urlopen(full, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def stream(url, window=None, signals=None, count=None, timeout=60.0):
    """Yield payloads from the SSE feed (?stream=1)."""
    q = ["stream=1"]
    if window is not None:
        q.append(f"window={int(window)}")
    if signals:
        q.append("signals=" + ",".join(signals))
    if count is not None:
        q.append(f"count={int(count)}")
    full = url.rstrip("/") + "/debug/pulse?" + "&".join(q)
    with urllib.request.urlopen(full, timeout=timeout) as resp:
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: "):
                yield json.loads(line[len("data: "):])


def main(argv=None, out=None):
    ap = argparse.ArgumentParser(
        prog="ptop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("url", nargs="?", default="http://127.0.0.1:8000",
                    help="serving server base URL")
    ap.add_argument("--file", default=None,
                    help="render a recorded /debug/pulse JSON payload")
    ap.add_argument("--window", type=int, default=None,
                    help="seconds of history to request")
    ap.add_argument("--signals", default=None,
                    help="comma-separated signal-name prefixes")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval seconds (default 2)")
    ap.add_argument("--count", type=int, default=None,
                    help="frames to render before exiting")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--stream", action="store_true",
                    help="consume the SSE live feed instead of polling")
    ap.add_argument("--width", type=int, default=24,
                    help="sparkline width (default 24)")
    ap.add_argument("--no-color", action="store_true")
    args = ap.parse_args(argv)
    out = out or sys.stdout
    color = not args.no_color and getattr(out, "isatty", lambda: False)()
    signals = [s for s in (args.signals or "").split(",") if s] or None
    clear = getattr(out, "isatty", lambda: False)() and not args.once

    def show(payload):
        if clear:
            out.write("\x1b[2J\x1b[H")
        render(payload, out=out, width=args.width, color=color)
        out.flush()

    if args.file:
        with open(args.file) as f:
            show(json.load(f))
        return 0
    if args.stream:
        n = 0
        for payload in stream(args.url, window=args.window,
                              signals=signals, count=args.count):
            show(payload)
            n += 1
            if args.once or (args.count is not None and n >= args.count):
                break
        return 0
    frames = 0
    while True:
        show(fetch(args.url, window=args.window, signals=signals))
        frames += 1
        if args.once or (args.count is not None and frames >= args.count):
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(130)
