#!/bin/bash
# One-shot on-chip capture: run whenever the v5e tunnel is alive.
# Order: kernel validation (cheap, highest evidence value) → model
# benches → remat/batch sweep refinements. Everything appends to
# BENCH_HISTORY.jsonl / TPU_VALIDATION.json which are committed.
cd "$(dirname "$0")/.."
set -x

timeout 900 python tools/validate_tpu_kernels.py 2>&1 | tail -12

for m in resnet50 bert moe serving input; do
  timeout 900 python bench_models.py "$m" 2>&1 | tail -2
done

# autotune: search batch/remat/flash-block space, persist winner to
# TUNED.json (bench.py picks it up as its defaults)
timeout 7200 python tools/autotune.py 2>&1 | tail -8

# final driver-comparable headline at the tuned defaults (validation
# already ran above — skip the redundant pre-step)
PT_BENCH_SKIP_VALIDATE=1 timeout 1800 python bench.py 2>&1 | tail -1

# packed-document flashmask: 4 docs per 2048-ctx row — block-skip
# should convert the blocked cross-doc attention into real tok/s
PT_BENCH_SKIP_VALIDATE=1 PT_BENCH_DOCS=4 timeout 1200 python bench.py 2>&1 | tail -1

# serving throughput on-chip (VERDICT r2 item 8), fp and int8 KV cache
timeout 900 python bench_models.py serving 2>&1 | tail -2
PT_SERVE_CACHE=int8 timeout 900 python bench_models.py serving 2>&1 | tail -2
echo "CAPTURE_DONE"
