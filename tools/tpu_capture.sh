#!/bin/bash
# One-shot on-chip capture: run whenever the v5e tunnel is alive.
# Order: kernel validation (cheap, highest evidence value) → model
# benches → remat/batch sweep refinements. Everything appends to
# BENCH_HISTORY.jsonl / TPU_VALIDATION.json which are committed.
cd "$(dirname "$0")/.."
set -x

timeout 900 python tools/validate_tpu_kernels.py 2>&1 | tail -12

for m in resnet50 bert moe serving; do
  timeout 900 python bench_models.py "$m" 2>&1 | tail -2
done

# headline refinements: dots remat and batch 24 at the winning seq
for cfg in "16 2048 dots" "24 2048 true"; do
  set -- $cfg
  PT_BENCH_BATCH=$1 PT_BENCH_SEQ=$2 PT_BENCH_REMAT=$3 \
    timeout 900 python bench.py 2>&1 | tail -1
done
echo "CAPTURE_DONE"
