#!/bin/bash
# One-shot on-chip capture: run whenever the v5e tunnel is alive.
#
# r5 ordering (windows are short — 18-40 min observed): validation
# first (the cheapest REQUIRED artifact; compiles disk-cached from a
# previous window), then the stage-A MFU ladder (the north-star search;
# each trial banks its own BENCH_HISTORY entry at completion), then the
# headline at the tuned winner, then serving/models/BC refine. Between
# steps a cheap probe checks the tunnel is still alive and EXITS EARLY
# otherwise — a dead tunnel must not pin the caller for the summed step
# timeouts (the watch loop re-fires us on the next window; the
# persistent compilation cache makes the re-fire skip straight to
# execution for anything already compiled). Every step appends to
# BENCH_HISTORY.jsonl / TPU_VALIDATION.json which are committed.
cd "$(dirname "$0")/.."
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
# BC refine must only build on a stage-A winner banked by this WATCH
# session (tpu_watch.sh exports its start; standalone runs fall back to
# capture start) — a committed TUNED.json from a previous round must
# not serve as the refine base (autotune._tuned_defaults_for_refine)
export PT_TUNE_MIN_TS=${PT_TUNE_MIN_TS:-$(date +%s)}

alive() {
  # shared canary (tools/_tpu_canary.py): uncached tiny compile +
  # random-value execute — catches the "half-alive" mode (devices list
  # fine, remote compile/execute dead) and defeats both the disk cache
  # and the terminal's (executable, inputs) memoization. Single source
  # for all three probers (watch / capture / autotune).
  timeout 300 python tools/_tpu_canary.py 2>/dev/null
}
alive || { echo "CAPTURE_ABORT tunnel half-alive (compile canary failed)"; exit 2; }

# skip re-validation when a fresh passing result exists (a re-fired
# capture after a tunnel drop must spend its window on what's missing)
SKIP_VALIDATE=0
python - <<'EOF' && SKIP_VALIDATE=1
import json, os, sys, time
if not os.path.exists("TPU_VALIDATION.json"):
    sys.exit(1)
st = os.stat("TPU_VALIDATION.json")
ok = json.load(open("TPU_VALIDATION.json")).get("ok") is True
sys.exit(0 if (ok and time.time() - st.st_mtime < 6 * 3600) else 1)
EOF
set -x

# r5 reorder: validation FIRST (cheapest required artifact — compiles
# are disk-cached from the 00:09 window, ~8 min), then the stage-A MFU
# ladder (the north-star search; every trial banks its own
# BENCH_HISTORY entry at completion, so a mid-stage death keeps all
# finished trials), then the headline AT the tuned winner. The old
# order spent the first ~20 min of a window re-measuring known b16
# numbers before the search started.

# 1. kernel validation -> TPU_VALIDATION.json (five pallas families)
if [ "$SKIP_VALIDATE" != 1 ]; then
  timeout 5400 python tools/validate_tpu_kernels.py 2>&1 | tail -14
  alive || { echo "CAPTURE_ABORT tunnel dead after step 1"; exit 2; }
fi

# 2. autotune stage A (batch x remat x fused_ce — the strict-MFU
#    levers, 32/48/64 full-remat ladder first): a window that dies
#    during the long-tail benches below must not take the headline
#    search with it. A FRESH stage-A result from an earlier window of
#    this watch session is not re-run — the step jumps straight to the
#    BC refine so multi-window rounds make forward progress.
STAGE2=A
python - <<'EOF' && STAGE2=BC
import json, os, sys
try:
    d = json.load(open("TUNED.json"))
except Exception:
    sys.exit(1)
min_ts = float(os.environ.get("PT_TUNE_MIN_TS", "0"))
ok = (not d.get("smoke") and d.get("best")
      and "A" in d.get("stages_done", []) and d.get("ts", 0) >= min_ts)
sys.exit(0 if ok else 1)
EOF
PT_TUNE_STAGES=$STAGE2 PT_TUNE_TRIAL_TIMEOUT=2700 timeout 7200 \
  python tools/autotune.py 2>&1 | tail -6
TUNE_RC=${PIPESTATUS[0]}
[ "$TUNE_RC" != 0 ] && echo "stage $STAGE2 exited rc=$TUNE_RC (124=timeout); continuing"
alive || { echo "CAPTURE_ABORT tunnel dead after step 2"; exit 2; }

# 3. headline AT the stage-A winner (TUNED.json best is honored
#    automatically) — this is the driver-facing number. If stage A
#    banked no winner (TUNED.json has no best block), force the
#    fused-CE-on hand default rather than silently benching unfused.
HEADLINE_ENV=""
python - <<'EOF' || HEADLINE_ENV="PT_FUSED_CE=1"
import json, sys
d = json.load(open("TUNED.json"))
sys.exit(0 if (d.get("best") and not d.get("smoke")) else 1)
EOF
env $HEADLINE_ENV PT_BENCH_SKIP_VALIDATE=1 PT_BENCH_TIMEOUT=3300 \
  timeout 3600 python bench.py 2>&1 | tail -3
alive || { echo "CAPTURE_ABORT tunnel dead after step 3"; exit 2; }

# 4. packed-document flashmask: 4 docs per 2048-ctx row — block-skip
#    converts the blocked cross-doc attention into real tok/s
PT_BENCH_SKIP_VALIDATE=1 PT_FUSED_CE=1 PT_BENCH_DOCS=4 \
  PT_BENCH_TIMEOUT=3300 timeout 3600 python bench.py 2>&1 | tail -2
alive || { echo "CAPTURE_ABORT tunnel dead after step 4"; exit 2; }

# (no separate fused-CE ablation: stage A's list carries fused on/off
# at the leading batches, so the lever is quantified by the search)

# 5. serving throughput on-chip: fp, int8 KV cache, speculative decode
timeout 1800 python bench_models.py serving 2>&1 | tail -2
alive || { echo "CAPTURE_ABORT tunnel dead mid step 5"; exit 2; }
PT_SERVE_CACHE=int8 timeout 1800 python bench_models.py serving 2>&1 | tail -2
alive || { echo "CAPTURE_ABORT tunnel dead mid step 5 (int8)"; exit 2; }
PT_SERVE_SPEC=4 timeout 1800 python bench_models.py serving 2>&1 | tail -2
alive || { echo "CAPTURE_ABORT tunnel dead after step 5"; exit 2; }

# 5b. serving under load: Poisson arrivals, TTFT/TPOT percentiles,
#     fp/int8 x spec on/off in one table (VERDICT r5 item 4)
timeout 2700 python bench_models.py serving_load 2>&1 | tail -2
alive || { echo "CAPTURE_ABORT tunnel dead after step 5b"; exit 2; }

# 6. remaining per-model benches
for m in resnet50 bert moe input dlrm; do
  timeout 1800 python bench_models.py "$m" 2>&1 | tail -2
  alive || { echo "CAPTURE_ABORT tunnel dead during step 6 ($m)"; exit 2; }
done

# 7. autotune stage B/C: refine the step-2 stage-A winner (flash
#    blocks, n_micro). Checkpoints every improvement, so a mid-search
#    death keeps the best-so-far. Skipped when step 2 already ran the
#    BC refine (fresh stage-A shortcut).
if [ "$STAGE2" = A ]; then
  PT_TUNE_STAGES=BC PT_TUNE_TRIAL_TIMEOUT=2700 timeout 10800 \
    python tools/autotune.py 2>&1 | tail -8
fi

# 8. final headline at the tuned defaults
alive && PT_BENCH_SKIP_VALIDATE=1 timeout 3600 python bench.py 2>&1 | tail -1
echo "CAPTURE_DONE"
