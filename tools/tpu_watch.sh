#!/bin/bash
# Poll the TPU tunnel; on first successful device init, run the full
# on-chip capture suite (tools/tpu_capture.sh). Designed to run in the
# background for the whole round — exits after capture or ~10.5h.
cd "$(dirname "$0")/.."
LOG=tpu_watch.log
for i in $(seq 1 100); do
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>>"$LOG"; then
    echo "TPU alive at probe $i ($(date -u +%FT%TZ))" | tee -a "$LOG"
    bash tools/tpu_capture.sh 2>&1 | tee -a tpu_capture.log
    echo "CAPTURE_EXIT=$?" | tee -a "$LOG"
    exit 0
  fi
  echo "probe $i: tunnel down ($(date -u +%FT%TZ))" >>"$LOG"
  sleep 230
done
echo "TPU never came up this round ($(date -u +%FT%TZ))" | tee -a "$LOG"
exit 1
