#!/bin/bash
# Poll the TPU tunnel; every time it comes alive, run the on-chip
# capture suite (tools/tpu_capture.sh). r4: windows are SHORT (~18 min
# observed), so the loop keeps watching after a capture attempt and
# re-fires on the next window until ALL the round's key artifacts exist:
#   - TPU_VALIDATION.json with ok:true
#   - a TPU (non-cpu) llama entry in BENCH_HISTORY.jsonl newer than
#     this script's start
#   - a real (non-smoke) TUNED.json from an on-chip autotune search —
#     without this gate a window that banks validation+bench then dies
#     before step 7 would retire the watch with the strict-MFU search
#     never run
# The JAX persistent compilation cache makes re-fired captures skip
# straight to execution for anything already compiled in a previous
# window.
cd "$(dirname "$0")/.."
LOG=tpu_watch.log
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
START_TS=$(date +%s)
# BC-refine freshness is scoped to this WATCH session: a stage-A winner
# banked by an earlier window of the same session is a valid refine
# base for a later window's capture (tpu_capture.sh defaults this to
# its own start when run standalone)
export PT_TUNE_MIN_TS=$START_TS

have_artifacts() {
  python - "$START_TS" <<'EOF'
import json, sys, time
start = float(sys.argv[1])
try:
    ok = json.load(open("TPU_VALIDATION.json")).get("ok") is True
except Exception:
    ok = False
bench = False
try:
    for line in open("BENCH_HISTORY.jsonl"):
        try:
            e = json.loads(line)
        except Exception:
            continue
        if (e.get("extra", {}).get("backend") not in (None, "cpu")
                and e.get("ts", 0) >= start and "batch" in e):
            bench = True
except Exception:
    pass
tuned = False
try:
    t = json.load(open("TUNED.json"))
    # fresh (this watch run, not a committed file from a previous
    # round) AND the full A/B/C search finished — a mid-search tunnel
    # death persists best-so-far with partial stages, and later windows
    # should finish the job
    tuned = (not t.get("smoke")) and "C" in t.get("stages_done", []) \
        and t.get("ts", 0) >= start
except Exception:
    pass
sys.exit(0 if (ok and bench and tuned) else 1)
EOF
}

probe() {
  # shared canary (tools/_tpu_canary.py): uncached tiny compile +
  # random-value execute — a half-alive tunnel (devices list fine,
  # remote compile/execute dead — observed 2026-07-31) must read as
  # DOWN here, and neither the disk cache nor the terminal's
  # (executable, inputs) memoization can mask that. 180s: a live
  # tunnel answers in well under 2 min; the timeout plus the sleep
  # below is the window-discovery latency.
  timeout 180 python tools/_tpu_canary.py 2>>"$LOG"
}

state() {
  # machine-readable tunnel state for bench.py's fast-path: when the
  # watcher saw the tunnel down recently, bench.py skips its own probe
  # ladder and falls back to CPU within seconds (VERDICT r4 weak #3).
  printf '{"ts": %s, "up": %s}\n' "$(date +%s)" "$1" > .tpu_state.json.tmp \
    && mv .tpu_state.json.tmp .tpu_state.json
}

# wall-clock bound, not iteration count: a fail-fast down-probe
# (connection refused) makes cycles ~100s while a hanging one takes
# ~270s — an iteration budget would cut the watch's lifetime 3x
# depending on HOW the tunnel is down
i=0
while [ $(($(date +%s) - START_TS)) -lt $((16 * 3600)) ]; do
  i=$((i + 1))
  if probe; then
    state true
    echo "TPU alive at probe $i ($(date -u +%FT%TZ))" | tee -a "$LOG"
    bash tools/tpu_capture.sh 2>&1 | tee -a tpu_capture.log
    echo "CAPTURE_EXIT=${PIPESTATUS[0]} (probe $i)" | tee -a "$LOG"
    if have_artifacts; then
      echo "key artifacts banked; watch exiting ($(date -u +%FT%TZ))" | tee -a "$LOG"
      exit 0
    fi
    echo "artifacts incomplete; continuing to watch" | tee -a "$LOG"
  else
    state false
    echo "probe $i: tunnel down ($(date -u +%FT%TZ))" >>"$LOG"
  fi
  sleep 90
done
echo "watch window exhausted ($(date -u +%FT%TZ))" | tee -a "$LOG"
exit 1
