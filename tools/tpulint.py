#!/usr/bin/env python
"""tpulint — TPU-hostility static analysis over the paddle_tpu tree.

    python tools/tpulint.py paddle_tpu/ [--format json] [--list-rules]

Thin launcher: the implementation lives in paddle_tpu/analysis/. The
linter is pure stdlib ast, and this launcher loads it as a standalone
package (bypassing paddle_tpu/__init__.py) so CI boxes without an
accelerator stack can still run it. See docs/static_analysis.md for
the rule catalogue.
"""
import importlib
import importlib.util
import os
import sys


def _load_analysis():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkgdir = os.path.join(root, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "_tpulint_analysis", os.path.join(pkgdir, "__init__.py"),
        submodule_search_locations=[pkgdir])
    pkg = importlib.util.module_from_spec(spec)
    sys.modules["_tpulint_analysis"] = pkg
    spec.loader.exec_module(pkg)
    return importlib.import_module("_tpulint_analysis.cli")


if __name__ == "__main__":
    sys.exit(_load_analysis().main())
