"""Offline autotuner for the serving ragged-paged-attention kernel tile
(ISSUE 12; ROADMAP item-1 follow-on — "real-TPU tile-size tuning for
the kernel").

Sweeps legal (block_q, block_pages) tile configs of
`paddle_tpu.kernels.ragged_paged_attention` on the attached backend
over a serving-shaped problem (a decode+prefill wave), verifies every
candidate is BIT-identical to the default tile (the kernel's contract
— a tile choice must never change a sampled token), and persists the
per-TPU-generation winner into TUNED.kernels.json via
`_tuning_defaults.save_ragged_tile`. The serving engine loads that
file ONCE at construction (`load_ragged_tile(device_generation())`),
so a tuned tile is a static jit arg — it never retraces a live trace.

Run on a live chip:   python tools/tune_ragged.py
Re-tune a new chip generation: same command on that chip — winners key
by generation, so v5e and v6e entries coexist in one file.

Smoke mode (no hardware): --smoke (or PT_TUNE_SMOKE=1) runs the sweep
on CPU (interpret-mode pallas, tiny problem) and writes to
TUNED.kernels.smoke.json — never the file the engine reads — proving
the sweep/verify/persist/reload loop before an unattended tunnel
window. Docs: docs/tuning.md § Serving kernel autotune.

Env knobs:
  PT_TUNE_OUT            — output path override
  PT_RAGGED_TILE_FILE    — engine-side file override (tests point both
                           here for the roundtrip check)
  PT_TUNE_RAGGED_ITERS   — timed iterations per config (default 20)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)


def _load_defaults():
    import importlib.util
    p = os.path.join(ROOT, "paddle_tpu", "_tuning_defaults.py")
    spec = importlib.util.spec_from_file_location("_tuning_defaults", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_TD = _load_defaults()


def make_problem(smoke, seed=0):
    """A serving-shaped wave: prefill run + decodes + slack rows, GQA
    q/kv heads, paged KV. Smoke keeps every dim tiny (interpret-mode
    pallas multiplies cost ~100x)."""
    import numpy as np
    import jax.numpy as jnp

    if smoke:
        qh, kvh, d, page, pages_per_seq, slots, t = 4, 2, 16, 8, 4, 3, 16
    else:
        qh, kvh, d, page, pages_per_seq, slots, t = 32, 8, 128, 16, 32, 8, 64
    num_pages = slots * pages_per_seq + 1
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((t, qh, d)).astype(np.float32)
    kshape = (kvh, num_pages, page, d)
    k_pages = rng.standard_normal(kshape).astype(np.float32)
    v_pages = rng.standard_normal(kshape).astype(np.float32)
    ptab = np.arange(slots * pages_per_seq, dtype=np.int32).reshape(
        slots, pages_per_seq)
    # slot 0: a prefill run filling half the buffer; remaining slots:
    # deep decodes (max pages in play — the config that tiling moves);
    # tail: inactive slack rows, the kernel's early-exit path
    n_pf = t // 2
    tok_slot = np.zeros((t,), np.int32)
    tok_pos = np.full((t,), -1, np.int32)
    tok_pos[:n_pf] = np.arange(n_pf, dtype=np.int32)
    depth = pages_per_seq * page - 1
    for i, s in enumerate(range(1, slots)):
        row = n_pf + i
        if row >= t:
            break
        tok_slot[row] = s
        tok_pos[row] = depth - i
    return (jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(ptab), jnp.asarray(tok_slot), jnp.asarray(tok_pos))


def candidate_tiles(group, n_pages, smoke):
    """Legal (block_q, block_pages) grid: block_q sublane-aligned and
    >= the GQA group (0 = derive the seed shape), block_pages within
    the page-table depth. The seed tile (0, 1) always leads — it is
    the verified baseline every other config must bit-match."""
    from paddle_tpu.ops.paged_attention import MIN_GROUP

    gp_min = group + (-group) % MIN_GROUP
    qs = [0] + [gp_min * m for m in (2, 4)]
    ps = [1, 2, 4, 8]
    if smoke:
        qs, ps = [0, gp_min * 2], [1, 2]
    return [(bq, bp) for bq in qs for bp in ps
            if bp <= max(n_pages, 1)]


def time_config(fn, iters):
    import jax
    out = fn()                      # compile + correctness sample
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t0)
    times.sort()
    return out, times[len(times) // 2]   # median


def sweep(smoke, iters, use_pallas=None, interpret=None):
    import numpy as np
    import jax
    from paddle_tpu.kernels import ragged_paged_attention

    backend = jax.default_backend()
    if use_pallas is None:
        use_pallas = backend == "tpu" or smoke
    if interpret is None:
        interpret = backend != "tpu"
    q, k, v, ptab, slot, pos = make_problem(smoke)
    group = q.shape[1] // k.shape[0]
    n_pages = ptab.shape[1]
    rows = []
    base_out = None
    for bq, bp in candidate_tiles(group, n_pages, smoke):
        cfg = {"block_q": bq, "block_pages": bp}

        def run(bq=bq, bp=bp):
            return ragged_paged_attention(
                q, k, v, ptab, slot, pos, use_pallas=use_pallas,
                interpret=interpret, block_q=bq or None,
                block_pages=bp or None)
        try:
            out, t = time_config(run, iters)
        except Exception as e:   # Mosaic rejection on a real chip
            print(f"  tile {cfg} FAILED: {e}", flush=True)
            rows.append(dict(cfg, time_s=None, exact=False,
                             error=str(e)[:200]))
            continue
        out = np.asarray(out)
        if base_out is None:
            base_out = out           # the seed tile leads the grid
        exact = bool(np.array_equal(base_out, out))
        rows.append(dict(cfg, time_s=t, exact=exact))
        print(f"  tile {cfg}: {t * 1e6:.1f} us/call"
              f"{'' if exact else '  NOT BIT-IDENTICAL — rejected'}",
              flush=True)
    ok = [r for r in rows if r["time_s"] is not None and r["exact"]]
    if not ok:
        raise RuntimeError("every tile config failed or diverged")
    best = min(ok, key=lambda r: r["time_s"])
    return best, rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    default=os.environ.get("PT_TUNE_SMOKE") == "1",
                    help="CPU interpret-mode sweep; writes the smoke "
                         "file, never TUNED.kernels.json")
    ap.add_argument("--out", default=None, help="tile-file override")
    ap.add_argument("--iters", type=int, default=int(
        os.environ.get("PT_TUNE_RAGGED_ITERS", "20")))
    args = ap.parse_args(argv)

    if args.smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    backend = jax.default_backend()
    if not args.smoke and backend != "tpu":
        print("tune_ragged: TPU unreachable; not tuning (use --smoke "
              "for the CPU harness check)", file=sys.stderr)
        return 1
    out_path = args.out or os.environ.get("PT_TUNE_OUT") or (
        os.path.join(ROOT, "TUNED.kernels.smoke.json") if args.smoke
        else _TD.RAGGED_TILE_FILE)

    from paddle_tpu.observability.device_telemetry import device_generation
    gen = device_generation()
    print(f"tune_ragged: backend={backend} generation={gen} "
          f"out={os.path.basename(out_path)}"
          f"{' (SMOKE)' if args.smoke else ''}", flush=True)
    best, rows = sweep(args.smoke, args.iters)
    entry = _TD.save_ragged_tile(
        gen, best["block_q"], best["block_pages"], path=out_path,
        extra={"time_us": round(best["time_s"] * 1e6, 2),
               "smoke": args.smoke, "ts": time.time(),
               "trials": [{k: r.get(k) for k in
                           ("block_q", "block_pages", "time_s", "exact")}
                          for r in rows]})
    # reload through the engine's own loader: what we persisted is
    # exactly what a ServingEngine on this generation will pick up
    got = _TD.load_ragged_tile(gen, path=out_path)
    assert got == (best["block_q"], best["block_pages"]), got
    print(f"{os.path.basename(out_path)}[{_TD.generation_key(gen)}] <- "
          f"{entry}", flush=True)
    print(json.dumps({"generation": _TD.generation_key(gen),
                      "best": {"block_q": best["block_q"],
                               "block_pages": best["block_pages"]},
                      "time_us": round(best["time_s"] * 1e6, 2),
                      "n_trials": len(rows)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
