"""On-chip pallas kernel validation (VERDICT r1 weak #3).

Runs the hand-written pallas kernels on the REAL TPU (no interpret mode)
and checks them numerically against the XLA reference paths. tests/ pins
JAX_PLATFORMS=cpu for hermetic CI, so this script is the hardware-truth
companion: run it whenever the chip tunnel is alive.

    python tools/validate_tpu_kernels.py        # writes TPU_VALIDATION.json

Exit code 0 iff every kernel passes on-chip.

Tunnel windows are short (~18-90 min observed) and every config is a
separate remote compile, so the default run validates a CORE subset per
family — one config per distinct kernel code path (causal, bf16,
ragged-tail, int8, dropout). PT_VALIDATE_FULL=1 runs the full matrix;
the hermetic CPU interpret-mode tests in tests/ already sweep the full
matrix every CI run, so core-on-chip + full-in-interpret keeps coverage
while fitting a window.
"""
from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS = []
OUT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "TPU_VALIDATION.json")

# Fresh entropy per family unless pinned: the serving terminal memoizes
# (executable, inputs) → output across processes, so a fixed-seed
# re-validation of an unchanged kernel would "pass" from cache without
# proving the chip still executes. Random inputs make every run a real
# execution proof; the kernel-vs-reference comparison is unaffected
# (both sides see the same inputs). PT_VALIDATE_SEED pins for repro.
_PIN = os.environ.get("PT_VALIDATE_SEED")


def _rng(family_ordinal):
    if _PIN is not None:
        return np.random.RandomState(int(_PIN) + family_ordinal)
    return np.random.RandomState(
        int.from_bytes(os.urandom(4), "little"))


def _write(final_ok=None):
    """Progressive banking: a tunnel death mid-suite must still leave the
    families already proven on disk. ok stays false until the full suite
    passes (the watch loop / bench skip-logic key on ok:true)."""
    out = {"device": DEVICE[0], "ok": bool(final_ok),
           "complete": final_ok is not None, "results": RESULTS}
    tmp = OUT_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, OUT_PATH)


DEVICE = ["unknown"]


def check(name, fn):
    t0 = time.perf_counter()
    try:
        detail = fn()
        ok = True
    except Exception as e:  # noqa: BLE001 — record, keep validating the rest
        detail = f"{type(e).__name__}: {e}"
        ok = False
    dt = time.perf_counter() - t0
    RESULTS.append({"kernel": name, "ok": ok, "detail": detail,
                    "seconds": round(dt, 2)})
    _write()
    print(f"[{'PASS' if ok else 'FAIL'}] {name} ({dt:.1f}s): {detail}",
          flush=True)
    return ok


def max_err(a, b):
    return float(np.max(np.abs(np.asarray(a, np.float32) -
                               np.asarray(b, np.float32))))


FULL = os.environ.get("PT_VALIDATE_FULL") == "1"


def flash_fwd_bwd():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.flash_attention import (flash_attention_bhsd,
                                                mha_reference)
    rng = _rng(0)
    errs = {}
    configs = [
        ((2, 4, 512, 64), True, jnp.float32),
        ((1, 8, 1024, 128), True, jnp.bfloat16),
        ((2, 4, 384, 64), True, jnp.float32),  # ragged tail block
    ]
    if FULL:
        configs.insert(1, ((2, 4, 512, 64), False, jnp.float32))
    for (b, h, s, d), causal, dtype in configs:
        q = jnp.asarray(rng.randn(b, h, s, d), dtype) * 0.3
        k = jnp.asarray(rng.randn(b, h, s, d), dtype) * 0.3
        v = jnp.asarray(rng.randn(b, h, s, d), dtype) * 0.3
        scale = 1.0 / math.sqrt(d)

        def loss_pallas(q, k, v):
            o = flash_attention_bhsd(q, k, v, causal=causal, use_pallas=True,
                                     interpret=False)
            return (o * v).sum(), o

        def loss_ref(q, k, v):
            o, _ = mha_reference(q, k, v, None, causal, scale)
            return (o * v).sum(), o

        (_, o_p), g_p = jax.value_and_grad(loss_pallas, (0, 1, 2),
                                           has_aux=True)(q, k, v)
        (_, o_r), g_r = jax.value_and_grad(loss_ref, (0, 1, 2),
                                           has_aux=True)(q, k, v)
        # fp32 tolerance is MXU arithmetic, not kernel quality: on TPU
        # hardware a DEFAULT-precision fp32 dot runs as bf16 passes
        # (both in-kernel and in the XLA reference), so kernel-vs-
        # reference divergence is bf16 rounding-order — observed
        # 1.5-2.3e-3 on 0.3-scaled inputs across families. 2e-3 made
        # this a coin flip per random draw (flashmask failed a window
        # at 2.28e-3 while dense flash passed at 1.52e-3).
        tol = 2e-2 if dtype == jnp.bfloat16 else 5e-3
        eo = max_err(o_p, o_r)
        eg = max(max_err(a, b) for a, b in zip(g_p, g_r))
        # grads scale with S; compare relative to magnitude
        gmag = max(float(np.abs(np.asarray(g, np.float32)).max())
                   for g in g_r)
        key = f"{b}x{h}x{s}x{d}{'c' if causal else ''}-{jnp.dtype(dtype).name}"
        errs[key] = (round(eo, 5), round(eg / max(gmag, 1.0), 5))
        assert eo < tol, f"{key}: fwd err {eo}"
        assert eg / max(gmag, 1.0) < tol, f"{key}: bwd rel err {eg / gmag}"
    return errs


def varlen_fwd_bwd():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.varlen_attention import (flash_attn_unpadded,
                                                 varlen_reference,
                                                 seg_ids_from_cu_seqlens)
    rng = _rng(1)
    h, d = 4, 64
    lens = [200, 56, 312, 8]
    cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
    total = int(cu[-1])
    errs = {}
    for causal in ((True, False) if FULL else (True,)):
        q = jnp.asarray(rng.randn(total, h, d), jnp.float32) * 0.3
        k = jnp.asarray(rng.randn(total, h, d), jnp.float32) * 0.3
        v = jnp.asarray(rng.randn(total, h, d), jnp.float32) * 0.3
        seg = seg_ids_from_cu_seqlens(cu, total)
        scale = 1.0 / math.sqrt(d)

        def loss_pallas(q, k, v):
            o, _ = flash_attn_unpadded(q, k, v, cu, cu, causal=causal,
                                       use_pallas=True, interpret=False)
            return (o * v).sum(), o

        def loss_ref(q, k, v):
            qh = jnp.swapaxes(q, 0, 1)
            kh = jnp.swapaxes(k, 0, 1)
            vh = jnp.swapaxes(v, 0, 1)
            o, _ = varlen_reference(qh, kh, vh, seg, seg, causal, scale)
            return (jnp.swapaxes(o, 0, 1) * v).sum(), o

        (_, o_p), g_p = jax.value_and_grad(loss_pallas, (0, 1, 2),
                                           has_aux=True)(q, k, v)
        (_, _), g_r = jax.value_and_grad(loss_ref, (0, 1, 2),
                                         has_aux=True)(q, k, v)
        eg = max(max_err(a, b) for a, b in zip(g_p, g_r))
        gmag = max(float(np.abs(np.asarray(g, np.float32)).max())
                   for g in g_r)
        errs[f"causal={causal}"] = round(eg / max(gmag, 1.0), 5)
        # 5e-3: same fp32-on-hardware bf16-pass argument as flash tol
        assert eg / max(gmag, 1.0) < 5e-3
    return errs


def paged_decode():
    import jax.numpy as jnp
    from paddle_tpu.ops.paged_attention import (paged_attention,
                                                paged_attention_reference)
    rng = _rng(2)
    b, qh, kvh, d = 4, 8, 4, 64
    page_size, num_pages, pages_per_seq = 16, 64, 8
    q = jnp.asarray(rng.randn(b, qh, d), jnp.float32) * 0.3
    k_pages = jnp.asarray(rng.randn(kvh, num_pages, page_size, d),
                          jnp.float32) * 0.3
    v_pages = jnp.asarray(rng.randn(kvh, num_pages, page_size, d),
                          jnp.float32) * 0.3
    table = jnp.asarray(rng.permutation(num_pages)[:b * pages_per_seq]
                        .reshape(b, pages_per_seq), jnp.int32)
    lengths = jnp.asarray([100, 17, 128, 64], jnp.int32)
    scale = d ** -0.5
    o_p = paged_attention(q, k_pages, v_pages, table, lengths,
                          use_pallas=True)
    o_r = paged_attention_reference(q, k_pages, v_pages, table, lengths,
                                    scale)
    err = max_err(o_p, o_r)
    assert err < 2e-3, f"paged decode err {err}"
    # int8 cache variant: the quant kernel (scale blocks, reordered
    # operands) must be chip-proven against the XLA dequant path before
    # tpu_capture.sh benches PT_SERVE_CACHE=int8 (docs/tuning.md rule:
    # validate before benchmarking)
    from paddle_tpu.ops.paged_attention import quantize_kv
    kq, ks = quantize_kv(k_pages)
    vq, vs = quantize_kv(v_pages)
    oq_p = paged_attention(q, kq, vq, table, lengths, use_pallas=True,
                           k_scale=ks, v_scale=vs)
    oq_r = paged_attention_reference(q, kq, vq, table, lengths, scale,
                                     k_scale=ks, v_scale=vs)
    err_q = max_err(oq_p, oq_r)
    assert err_q < 2e-3, f"int8 paged decode err {err_q}"
    # and the quantized result tracks the fp result within quant noise
    err_qfp = max_err(oq_r, o_r)
    assert err_qfp < 0.05, f"int8-vs-fp decode err {err_qfp}"

    # multi-query verify kernel (speculative decoding / chunked
    # prefill): per-row causal limit, G chunk tokens per sequence —
    # distinct code path from the single-token kernel, chip-proven here
    from paddle_tpu.ops.paged_attention import (paged_verify_attention,
                                                paged_verify_reference)
    errs_v = {}
    base = jnp.asarray([90, 10, 120, 60], jnp.int32)
    for G in (4, 3):   # 3: odd chunk exercises the row-padding path
        qv = jnp.asarray(rng.randn(b, qh, G, d), jnp.float32) * 0.3
        ov_p = paged_verify_attention(qv, k_pages, v_pages, table, base,
                                      use_pallas=True)
        ov_r = paged_verify_reference(qv, k_pages, v_pages, table, base)
        err_v = max_err(ov_p, ov_r)
        assert err_v < 2e-3, f"verify-chunk G={G} err {err_v}"
        errs_v[f"verify_chunk_g{G}"] = round(err_v, 6)
    return dict({"max_err": round(err, 6), "max_err_int8": round(err_q, 6),
                 "int8_vs_fp": round(err_qfp, 6)}, **errs_v)


def flashmask_fwd_bwd():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.flashmask_attention import (flashmask_attention_bhsd,
                                                    flashmask_reference)
    rng = _rng(3)
    errs = {}
    configs = [
        ((2, 2, 512, 64), True, 1),    # document-causal cutoff
        ((1, 2, 512, 128), False, 2),  # bidirectional start/end
    ]
    if FULL:
        configs += [
            ((2, 2, 512, 64), True, 2),    # causal band
            ((1, 2, 384, 64), True, 1),    # ragged tail block
        ]
    for (b, h, s, d), causal, n in configs:
        q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
        k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
        v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
        if causal and n == 1:
            sri = rng.randint(1, s + 1, (b, h, s, 1))
        elif causal and n == 2:
            st = rng.randint(0, s, (b, h, s, 1))
            sri = np.concatenate(
                [st, np.minimum(st + rng.randint(0, s // 2, st.shape), s)],
                -1)
        else:
            sri = np.concatenate([rng.randint(s // 2, s + 1, (b, h, s, 1)),
                                  rng.randint(0, s // 2, (b, h, s, 1))], -1)
        sri = jnp.asarray(sri, jnp.int32)

        def loss_k(q_, k_, v_):
            o = flashmask_attention_bhsd(q_, k_, v_, sri, causal=causal,
                                         use_pallas=True, interpret=False)
            return (o * v_).sum(), o

        def loss_r(q_, k_, v_):
            o, _ = flashmask_reference(q_, k_, v_, sri, causal, None)
            return (o * v_).sum(), o

        (_, o_k), g_k = jax.value_and_grad(loss_k, (0, 1, 2),
                                           has_aux=True)(q, k, v)
        (_, o_r), g_r = jax.value_and_grad(loss_r, (0, 1, 2),
                                           has_aux=True)(q, k, v)
        eo = max_err(o_k, o_r)
        eg = max(max_err(a, b2) for a, b2 in zip(g_k, g_r))
        gmag = max(float(np.abs(np.asarray(g, np.float32)).max())
                   for g in g_r)
        key = f"{b}x{h}x{s}x{d}{'c' if causal else ''}n{n}"
        errs[key] = (round(eo, 5), round(eg / max(gmag, 1.0), 5))
        # 5e-3: fp32-on-hardware is bf16-pass MXU arithmetic on both
        # sides of the comparison (see flash_fwd_bwd tol note)
        assert eo < 5e-3, f"{key}: fwd err {eo}"
        assert eg / max(gmag, 1.0) < 5e-3, f"{key}: bwd rel err"

    # in-kernel dropout (r4): fwd+bwd vs the dense reference applying
    # the SAME counter-based mask — must be bit-tight, and must run on
    # the real chip (uint32 hash ops in Mosaic) before any training
    # config relies on it
    b, h, s, d, rate, seed = 2, 2, 512, 64, 0.3, 123
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.3
    sri = jnp.asarray(rng.randint(1, s + 1, (b, h, s, 1)), jnp.int32)

    def loss_kd(q_, k_, v_):
        o = flashmask_attention_bhsd(q_, k_, v_, sri, causal=True,
                                     use_pallas=True, interpret=False,
                                     dropout=rate, dropout_seed=seed)
        return (o * v_).sum(), o

    def loss_rd(q_, k_, v_):
        o, _ = flashmask_reference(q_, k_, v_, sri, True, None,
                                   dropout=rate, dropout_seed=seed)
        return (o * v_).sum(), o

    (_, o_k), g_k = jax.value_and_grad(loss_kd, (0, 1, 2),
                                       has_aux=True)(q, k, v)
    (_, o_r), g_r = jax.value_and_grad(loss_rd, (0, 1, 2),
                                       has_aux=True)(q, k, v)
    eo = max_err(o_k, o_r)
    eg = max(max_err(a, b2) for a, b2 in zip(g_k, g_r))
    gmag = max(float(np.abs(np.asarray(g, np.float32)).max()) for g in g_r)
    errs["dropout0.3"] = (round(eo, 5), round(eg / max(gmag, 1.0), 5))
    # 8e-3, not the 5e-3 of the mask-free cases: the 1/(1-p) rescale
    # amplifies fp accumulation noise ~1.43x over the mask-free
    # fp32-on-hardware band (observed up to 2.3e-3, bounded at 5e-3),
    # and dropping 30% of the summands changes accumulation order.
    # Chip-verified 2026-08-01 that the error is DIFFUSE (mean 8.6e-5,
    # zero elements > 5e-3 of 131k) — a kernel/reference mask
    # disagreement would show isolated per-position errors at the
    # magnitude of whole attention weights.
    assert eo < 8e-3, f"dropout fwd err {eo}"
    assert eg / max(gmag, 1.0) < 8e-3, "dropout bwd rel err"
    return errs


def flash_bf16_long():
    """bf16 @ 4096 ctx — the bench's serving-relevant shape, on-chip."""
    import jax.numpy as jnp
    from paddle_tpu.ops.flash_attention import (flash_attention_bhsd,
                                                mha_reference)
    rng = _rng(4)
    b, h, s, d = 1, 4, 4096, 128
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16) * 0.3
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16) * 0.3
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16) * 0.3
    o_p = flash_attention_bhsd(q, k, v, causal=True, use_pallas=True,
                               interpret=False)
    o_r, _ = mha_reference(q, k, v, None, True, 1.0 / math.sqrt(d))
    err = max_err(o_p, o_r)
    assert err < 3e-2, f"bf16 long-ctx err {err}"
    return {"max_err": round(err, 5)}


def main():
    import jax
    dev = jax.devices()[0]
    assert dev.platform != "cpu", f"not on TPU: {dev}"
    DEVICE[0] = str(dev)
    print(f"validating on {dev} (jax {jax.__version__})", flush=True)
    ok = True
    ok &= check("flash_attention fwd+bwd", flash_fwd_bwd)
    ok &= check("varlen flash_attn_unpadded fwd+bwd", varlen_fwd_bwd)
    ok &= check("paged_attention decode", paged_decode)
    ok &= check("flashmask fwd+bwd", flashmask_fwd_bwd)
    ok &= check("flash bf16 4k-ctx", flash_bf16_long)
    _write(final_ok=ok)
    print(json.dumps({"ok": bool(ok)}))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
